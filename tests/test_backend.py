"""Backend abstraction tests — local + mem parity, meta/index round-trips,
compaction marking, caching reader (reference: tempodb/backend/*_test.go)."""

import pytest

from tempo_tpu.backend import (
    BlockMeta,
    CacheProvider,
    CachingReader,
    DedicatedColumn,
    DoesNotExist,
    KeyPath,
    LocalBackend,
    MemBackend,
    block_keypath,
    blocks,
    clear_block,
    has_meta,
    mark_block_compacted,
    read_block_meta,
    read_compacted_block_meta,
    read_tenant_index,
    tenants,
    write_block_meta,
    write_tenant_index,
)
from tempo_tpu.backend.cloud import open_backend


@pytest.fixture(params=["mem", "local", "s3", "azure"])
def backend(request, tmp_path):
    if request.param == "mem":
        return MemBackend()
    if request.param == "s3":
        from tests.mock_s3 import ACCESS_KEY, REGION, SECRET_KEY, start_mock_s3

        srv, port, _cls = start_mock_s3()
        request.addfinalizer(srv.shutdown)
        b = open_backend(
            "s3", bucket="test-bucket", endpoint=f"127.0.0.1:{port}",
            region=REGION, access_key=ACCESS_KEY, secret_key=SECRET_KEY,
            insecure=True)
        return b
    if request.param == "azure":
        from tests.mock_azure import (ACCOUNT, ACCOUNT_KEY, CONTAINER,
                                      start_mock_azure)

        srv, port, _cls = start_mock_azure()
        request.addfinalizer(srv.shutdown)
        return open_backend(
            "azure", container_name=CONTAINER,
            storage_account_name=ACCOUNT, storage_account_key=ACCOUNT_KEY,
            endpoint=f"http://127.0.0.1:{port}")
    return LocalBackend(str(tmp_path / "store"))


def test_raw_roundtrip(backend):
    kp = block_keypath("b1", "tenant-a")
    backend.write("data.bin", kp, b"hello world")
    assert backend.read("data.bin", kp) == b"hello world"
    assert backend.read_range("data.bin", kp, 6, 5) == b"world"
    assert backend.size("data.bin", kp) == 11
    with pytest.raises(DoesNotExist):
        backend.read("nope", kp)


def test_listing_layout(backend):
    for tenant in ("t1", "t2"):
        for b in ("b1", "b2"):
            backend.write("meta.json", block_keypath(b, tenant), b"{}")
    assert tenants(backend) == ["t1", "t2"]
    assert blocks(backend, "t1") == ["b1", "b2"]
    assert backend.find(KeyPath(("t1",)), suffix="meta.json") == [
        "b1/meta.json", "b2/meta.json"]


def test_delete(backend):
    kp = block_keypath("b1", "t")
    backend.write("a", kp, b"1")
    backend.write("b", kp, b"2")
    backend.delete("a", kp)
    with pytest.raises(DoesNotExist):
        backend.read("a", kp)
    assert backend.read("b", kp) == b"2"
    clear_block(backend, "b1", "t")
    assert blocks(backend, "t") == []


def test_append_stream(backend):
    kp = block_keypath("b1", "t")
    tracker = None
    for chunk in (b"aa", b"bb", b"cc"):
        tracker = backend.append("obj", kp, tracker, chunk)
    backend.close_append("obj", kp, tracker)
    assert backend.read("obj", kp) == b"aabbcc"


def test_block_meta_roundtrip(backend):
    meta = BlockMeta.new(
        "t1", start_time=100.0, end_time=200.0, total_objects=10,
        total_spans=55, size_bytes=1234, compaction_level=1,
        dedicated_columns=[DedicatedColumn("span", "http.status_code", "int")],
    )
    write_block_meta(backend, meta)
    got = read_block_meta(backend, meta.block_id, "t1")
    assert got == meta
    assert has_meta(backend, meta.block_id, "t1") == (True, False)


def test_compaction_marking(backend):
    meta = BlockMeta.new("t1", total_spans=5)
    write_block_meta(backend, meta)
    mark_block_compacted(backend, backend, meta.block_id, "t1")
    assert has_meta(backend, meta.block_id, "t1") == (False, True)
    cm = read_compacted_block_meta(backend, meta.block_id, "t1")
    assert cm.meta == meta
    assert cm.compacted_time > 0


def test_tenant_index_roundtrip(backend):
    metas = [BlockMeta.new("t1", total_spans=i) for i in range(3)]
    write_tenant_index(backend, "t1", metas, [])
    idx = read_tenant_index(backend, "t1")
    assert [m.total_spans for m in idx.metas] == [0, 1, 2]
    assert idx.created_at > 0


def test_caching_reader():
    mem = MemBackend()
    kp = block_keypath("b1", "t")
    mem.write("bloom-0", kp, b"BLOOM")
    mem.write("data.parquet", kp, b"0123456789")
    r = CachingReader(mem, CacheProvider())
    assert r.read("bloom-0", kp) == b"BLOOM"
    assert r.read("bloom-0", kp) == b"BLOOM"
    assert mem.reads == 1  # second bloom read served from cache
    assert r.read_range("data.parquet", kp, 2, 3) == b"234"
    assert r.read_range("data.parquet", kp, 2, 3) == b"234"
    # uncached role: data reads always hit the backend
    assert r.read("data.parquet", kp) == b"0123456789"
    assert r.read("data.parquet", kp) == b"0123456789"
    assert mem.reads == 4


def test_open_backend_factory(tmp_path):
    from tempo_tpu.backend.s3 import S3Backend

    assert isinstance(open_backend("mem"), MemBackend)
    assert isinstance(open_backend("local", path=str(tmp_path / "x")), LocalBackend)
    s3 = open_backend("s3", bucket="b", access_key="k", secret_key="s")
    assert isinstance(s3, S3Backend)
    # gcs = the same client via the S3-interop XML API
    gcs = open_backend("gcs", bucket="b", access_key="k", secret_key="s")
    assert isinstance(gcs, S3Backend)
    assert "storage.googleapis.com" in gcs.base
    with pytest.raises((ValueError, TypeError)):
        open_backend("s3")   # bucket required
    from tempo_tpu.backend.azure import AzureBackend
    az = open_backend("azure", container_name="c", storage_account_name="a",
                      storage_account_key="")
    assert isinstance(az, AzureBackend)
    with pytest.raises((ValueError, TypeError)):
        open_backend("azure")   # container required
    with pytest.raises(ValueError):
        open_backend("bogus")


def test_tempodb_over_s3_with_hedged_reads():
    """Write/search/trace-by-id against the mock S3 endpoint through the
    full TempoDB stack with the hedged reader wired — the deployment shape
    of `tempodb/backend/s3/s3.go:25,129`."""
    import time

    from tests.mock_s3 import ACCESS_KEY, REGION, SECRET_KEY, start_mock_s3
    from tempo_tpu.db.tempodb import TempoDB
    from tempo_tpu.utils.hedging import HedgedReader

    srv, port, _cls = start_mock_s3()
    try:
        be = open_backend(
            "s3", bucket="test-bucket", endpoint=f"127.0.0.1:{port}",
            region=REGION, access_key=ACCESS_KEY, secret_key=SECRET_KEY,
            insecure=True, prefix="traces")
        db = TempoDB(HedgedReader(be, delay_s=0.5), be)
        t0 = int((time.time() - 60) * 1e9)
        tid = bytes.fromhex("11" * 16)
        spans = [{"trace_id": tid, "span_id": b"\x01" * 8, "name": "s3-op",
                  "kind": 2, "service": "s3-svc",
                  "start_unix_nano": t0, "end_unix_nano": t0 + 1_000_000,
                  "res_attrs": {"service.name": "s3-svc"}}]
        meta = db.write_block("tenant-s3", [(tid, spans)])
        assert meta.size_bytes > 0
        db.poll_now()
        assert [m.block_id for m in db.blocks("tenant-s3")] == [meta.block_id]
        found = db.find_trace_by_id("tenant-s3", tid)
        assert found and found[0]["name"] == "s3-op"
        res = db.search("tenant-s3", '{ resource.service.name = "s3-svc" }',
                        limit=5)
        assert len(res) == 1
    finally:
        srv.shutdown()
