"""Read path: querier fan-out + frontend sharding/queueing/combining."""

from __future__ import annotations

import numpy as np
import pytest

from tempo_tpu.backend.mem import MemBackend
from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig
from tempo_tpu.frontend import Frontend, FrontendConfig, RequestQueue
from tempo_tpu.frontend.sharders import (
    backend_search_jobs,
    time_windows,
    trace_id_shards,
)
from tempo_tpu.frontend.slos import SLOConfig, SLORecorder
from tempo_tpu.ingester import Ingester, IngesterConfig
from tempo_tpu.ingester.instance import InstanceConfig
from tempo_tpu.querier import Querier
from tempo_tpu.ring import ACTIVE, InstanceDesc, Ring
from tempo_tpu.ring.ring import _instance_tokens

T0 = 1_700_000_000.0


def mkspan(tid, sid, name="op", svc="svc", t0_s=T0, dur_ms=50, **kw):
    t0 = int(t0_s * 1e9)
    return {"trace_id": tid, "span_id": sid, "name": name, "service": svc,
            "start_unix_nano": t0, "end_unix_nano": t0 + int(dur_ms * 1e6), **kw}


@pytest.fixture
def stack(tmp_path):
    """backend blocks + one ingester with recent data + frontend/querier."""
    clock = [T0 + 3600.0]
    now = lambda: clock[0]
    be = MemBackend()
    db = TempoDB(be, be)
    # old data: 2 blocks in the backend (written 1h ago). RF1, like
    # generator-localblocks output — the only blocks metrics may read.
    for blk in range(2):
        traces = []
        for i in range(1, 6):
            tid = bytes([blk * 16 + i]) * 16
            traces.append((tid, [mkspan(tid, bytes([i]) * 8,
                                        svc=f"svc-{blk}", t0_s=T0 + i)]))
        db.write_block("t1", traces, replication_factor=1)
    db.poll_now()
    # recent data: one ingester with live traces (now)
    ring = Ring(replication_factor=1, now=now)
    ing = Ingester(str(tmp_path / "ing"), flush_writer=be,
                   cfg=IngesterConfig(instance=InstanceConfig()),
                   now=now, instance_id="ing-0")
    ring.register(InstanceDesc(id="ing-0", state=ACTIVE,
                               tokens=_instance_tokens("ing-0", 64),
                               heartbeat_ts=now()))
    rid = b"\xaa" * 16
    ing.push("t1", [(rid, [mkspan(rid, b"\x01" * 8, svc="recent-svc",
                                  t0_s=now() - 10)])])
    q = Querier(db, ring, {"ing-0": ing},
                cfg=__import__("tempo_tpu.querier.querier", fromlist=["QuerierConfig"]).QuerierConfig(rf=1))
    fe = Frontend(db, q, cfg=FrontendConfig(
        target_bytes_per_job=1,   # force many row-group jobs
        slo={"search": SLOConfig(duration_slo_s=60.0)}), now=now)
    return clock, now, be, db, ring, ing, q, fe, rid


def test_time_windows_split():
    now = 10_000.0
    ing, be = time_windows(now, 0.0, now, backend_after_s=900,
                           ingesters_until_s=1800)
    assert ing == (now - 1800, now)
    assert be == (0.0, now - 900)
    # all-recent query: no backend window
    ing2, be2 = time_windows(now, now - 60, now, 900, 1800)
    assert be2 is None and ing2 == (now - 60, now)


def test_backend_jobs_target_bytes(stack):
    clock, now, be, db, *_ = stack
    metas = db.blocklist.metas("t1")
    jobs = backend_search_jobs("t1", metas, 0, now(), target_bytes_per_job=1)
    # 1 byte/job target → one job per row group
    assert len(jobs) == sum(m.row_group_count for m in metas)
    jobs_big = backend_search_jobs("t1", metas, 0, now(),
                                   target_bytes_per_job=10 ** 9)
    assert len(jobs_big) == len(metas)


def test_frontend_search_merges_recent_and_backend(stack):
    clock, now, be, db, ring, ing, q, fe, rid = stack
    res = fe.search("t1", "{ }", limit=50, start_s=0, end_s=now())
    svcs = {r.root_service_name for r in res}
    assert "recent-svc" in svcs          # via ingester window
    assert "svc-0" in svcs and "svc-1" in svcs  # via backend jobs
    assert len(res) == 11
    # SLO recorded
    assert fe.slos.within[("search", "t1")] == 1


def test_frontend_search_filters(stack):
    clock, now, be, db, ring, ing, q, fe, rid = stack
    res = fe.search("t1", '{ resource.service.name = "svc-1" }',
                    limit=50, start_s=0, end_s=now())
    assert len(res) == 5
    assert all(r.root_service_name == "svc-1" for r in res)


def test_frontend_early_exit_limit(stack):
    clock, now, be, db, ring, ing, q, fe, rid = stack
    res = fe.search("t1", "{ }", limit=3, start_s=0, end_s=now())
    assert len(res) == 3


def test_find_trace_combines_ingester_and_backend(stack):
    clock, now, be, db, ring, ing, q, fe, rid = stack
    spans = fe.find_trace("t1", rid)
    assert spans is not None and len(spans) == 1
    old = fe.find_trace("t1", bytes([1]) * 16)
    assert old is not None and old[0]["name"] == "op"
    assert fe.find_trace("t1", b"\x77" * 16) is None


def test_frontend_query_range_over_blocks(stack):
    clock, now, be, db, ring, ing, q, fe, rid = stack
    series = fe.query_range("t1", "{ } | rate()",
                            start_s=T0 - 60, end_s=T0 + 600, step_s=60.0)
    assert series
    total = sum(float(np.nansum(s.samples)) for s in series)
    assert total > 0


def test_frontend_query_range_quantile(stack):
    clock, now, be, db, ring, ing, q, fe, rid = stack
    series = fe.query_range(
        "t1", "{ } | quantile_over_time(duration, .5)",
        start_s=T0 - 60, end_s=T0 + 600, step_s=660.0)
    vals = [v for s in series for v in s.samples if np.isfinite(v) and v > 0]
    assert vals
    # durations are 50ms; log2 quantile estimate must land within 2x
    assert 0.02 < vals[0] < 0.2


def test_queue_tenant_fairness():
    q = RequestQueue(max_outstanding_per_tenant=10)
    for i in range(6):
        q.enqueue("a", f"a{i}")
    q.enqueue("b", "b0")
    seen = []
    while True:
        batch = q.dequeue_batch(2)
        if not batch:
            break
        seen.append(batch)
    flat = [x for b in seen for x in b]
    assert set(flat) == {"a0", "a1", "a2", "a3", "a4", "a5", "b0"}
    # tenant b served before tenant a exhausts (round-robin)
    b_pos = flat.index("b0")
    assert b_pos < 6


def test_queue_outstanding_cap():
    from tempo_tpu.frontend.queue import QueueFull
    q = RequestQueue(max_outstanding_per_tenant=2)
    q.enqueue("a", 1)
    q.enqueue("a", 2)
    with pytest.raises(QueueFull):
        q.enqueue("a", 3)


def test_frontend_with_worker_pool(stack):
    clock, now, be, db, ring, ing, q, fe, rid = stack
    fe.start_workers(2)
    try:
        res = fe.search("t1", "{ }", limit=50, start_s=0, end_s=now())
        assert len(res) == 11
    finally:
        fe.shutdown()


def test_trace_id_shards_cover_space():
    shards = trace_id_shards(4)
    assert len(shards) == 4
    assert shards[0][0] == b"\x00" * 16
    assert shards[-1][1] == b"\xff" * 16
    for (lo, hi), (lo2, _) in zip(shards, shards[1:]):
        assert hi > lo
        assert lo2 == hi  # shared boundaries: no gap, no overlap


def test_slo_recorder_throughput_criterion():
    r = SLORecorder({"search": SLOConfig(duration_slo_s=1.0,
                                         throughput_bytes_slo=1000.0)})
    assert r.record("search", "t", 0.5, 0) is True            # fast
    assert r.record("search", "t", 5.0, 100_000) is True      # slow but hefty
    assert r.record("search", "t", 5.0, 100) is False         # slow and small
    assert r.total[("search", "t")] == 3
    assert r.within[("search", "t")] == 2
