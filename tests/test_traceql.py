"""TraceQL parser + evaluator tests (modeled on the reference's
`pkg/traceql/parse_test.go` and `ast_execute_test.go` table style)."""

import numpy as np
import pytest

from tempo_tpu.traceql import ast as A
from tempo_tpu.traceql import parse, ParseError
from tempo_tpu.traceql.conditions import extract_conditions
from tempo_tpu.traceql.eval import evaluate_pipeline
from tempo_tpu.traceql.memview import view_from_traces

# ---------------------------------------------------------------------------
# parse
# ---------------------------------------------------------------------------

ROUND_TRIPS = [
    "{ }",
    '{ .foo = "bar" }',
    "{ span.http.status_code >= 500 }",
    '{ (resource.service.name = "api") && (duration > 100ms) }',
    "{ (status = error) || (status = unset) }",
    "{ kind = server }",
    '{ name =~ "GET.*" } | count() > 2',
    "{ .a } && { .b }",
    "{ .a } >> { .b } | avg(duration) > 1s",
    "{ } | by(resource.service.name) | count() > 10 | coalesce()",
    "{ parent.span.foo = 1 }",
    '{ (trace:id = "abc") && (span:id != "def") }',
    '{ event:name = "exception" }',
    "{ duration > 1s } | rate() by(span.http.status_code)",
    "{ } | quantile_over_time(duration, 0.5, 0.99) by(span.region)",
    "{ status = error } | count_over_time() with (exemplars=true)",
    "{ } | histogram_over_time(duration)",
    "{ .a = 1 } !>> { .b = 2 }",
    "{ .a = 1 } &~ { .b = 2 }",
    "{ childCount > 3 }",
    '{ span."attr with space" = true }',
    "{ nestedSetParent = -1 }",
]


@pytest.mark.parametrize("q", ROUND_TRIPS)
def test_parse_round_trip(q):
    assert str(parse(str(parse(q)))) == str(parse(q))


@pytest.mark.parametrize("q", [
    "{",
    "{ .foo = }",
    "{ .foo ! 3 }",
    "{ } | frobnicate()",
    "{ } | count(",
    "{ } | rate() by(",
    "{ span: }",
    "{ trace:nope = 1 }",
])
def test_parse_errors(q):
    with pytest.raises(ParseError):
        parse(q)


def test_duration_units():
    p = parse("{ duration > 1h30m }")
    cond = p.stages[0].expr
    assert cond.rhs.value == 90 * 60 * 1_000_000_000
    assert parse("{ duration > 100ms }").stages[0].expr.rhs.value == 100_000_000


def test_status_enum_order_matches_reference():
    # error=0, ok=1, unset=2 (enum_statics.go)
    assert parse("{ status = error }").stages[0].expr.rhs.value == 0
    assert parse("{ status = ok }").stages[0].expr.rhs.value == 1
    assert parse("{ status = unset }").stages[0].expr.rhs.value == 2


# ---------------------------------------------------------------------------
# condition extraction
# ---------------------------------------------------------------------------

def test_conditions_all_and():
    req = extract_conditions(parse('{ .foo = "bar" && duration > 1s }'))
    assert req.all_conditions
    assert len(req.conditions) == 2


def test_conditions_or_clears_flag():
    req = extract_conditions(parse('{ .foo = "bar" || duration > 1s }'))
    assert not req.all_conditions
    assert len(req.conditions) == 2


def test_conditions_cross_attr_fetch_only():
    req = extract_conditions(parse("{ span.a > span.b }"))
    assert not req.all_conditions
    ops = {c.op for c in req.conditions}
    assert ops == {None}  # column fetches only


def test_conditions_structural_clears_flag():
    req = extract_conditions(parse("{ .a = 1 } >> { .b = 2 }"))
    assert not req.all_conditions
    assert len(req.conditions) == 2


# ---------------------------------------------------------------------------
# evaluation
# ---------------------------------------------------------------------------

def make_trace(tid, spans):
    """spans: list of (span_id, parent_id, name, dur_ms, extra)"""
    out = []
    for sid, pid, name, dur_ms, extra in spans:
        s = {
            "span_id": sid, "parent_span_id": pid, "name": name,
            "service": extra.get("service", "svc"),
            "kind": extra.get("kind", 2),
            "status_code": extra.get("status_code", 0),
            "start_unix_nano": extra.get("start", 1_000_000_000_000),
            "end_unix_nano": extra.get("start", 1_000_000_000_000) + dur_ms * 1_000_000,
            "attrs": extra.get("attrs", {}),
            "res_attrs": extra.get("res_attrs", {}),
            "events": extra.get("events", []),
        }
        out.append(s)
    return (tid, out)


@pytest.fixture
def view():
    t1 = make_trace(b"\x01" * 16, [
        (b"a" * 8, b"", "root", 100, {"attrs": {"http.status_code": 200}}),
        (b"b" * 8, b"a" * 8, "child1", 50,
         {"attrs": {"http.status_code": 500, "err": True}, "status_code": 2}),
        (b"c" * 8, b"b" * 8, "leaf", 20, {"attrs": {"region": "us"}}),
    ])
    t2 = make_trace(b"\x02" * 16, [
        (b"d" * 8, b"", "root2", 10, {"service": "other"}),
        (b"e" * 8, b"d" * 8, "child2", 5, {"attrs": {"region": "eu"}}),
    ])
    return view_from_traces([t1, t2])


def q(view, src):
    return evaluate_pipeline(parse(src), view)


def test_eval_name_filter(view):
    res = q(view, '{ name = "child1" }')
    assert len(res) == 1 and len(res[0].rows) == 1


def test_eval_attr_number(view):
    res = q(view, "{ span.http.status_code >= 500 }")
    assert sum(len(s.rows) for s in res) == 1


def test_eval_unscoped_fallback(view):
    res = q(view, "{ .region = `us` }")
    assert sum(len(s.rows) for s in res) == 1


def test_eval_status_error(view):
    res = q(view, "{ status = error }")
    assert sum(len(s.rows) for s in res) == 1


def test_eval_bool_bare_attr(view):
    res = q(view, "{ .err }")
    assert sum(len(s.rows) for s in res) == 1


def test_eval_duration(view):
    res = q(view, "{ duration >= 50ms }")
    assert sum(len(s.rows) for s in res) == 2  # root(100ms) + child1(50ms)


def test_eval_nil(view):
    res = q(view, "{ .region != nil }")
    assert sum(len(s.rows) for s in res) == 2


def test_eval_regex(view):
    res = q(view, '{ name =~ "child.*" }')
    assert sum(len(s.rows) for s in res) == 2
    res = q(view, '{ name !~ "child.*" }')
    assert sum(len(s.rows) for s in res) == 3  # root, leaf, root2


def test_eval_mismatched_types_false(view):
    res = q(view, '{ span.http.status_code = "500" }')
    assert sum(len(s.rows) for s in res) == 0


def test_eval_child_op(view):
    # {root} > {child}: children of root-matching spans
    res = q(view, '{ name = "root" } > { }')
    assert sum(len(s.rows) for s in res) == 1
    names = view.col("name").values[res[0].rows]
    assert list(names) == ["child1"]


def test_eval_descendant_op(view):
    res = q(view, '{ name = "root" } >> { }')
    assert sum(len(s.rows) for s in res) == 2  # child1, leaf


def test_eval_ancestor_op(view):
    res = q(view, '{ name = "leaf" } << { }')
    assert sum(len(s.rows) for s in res) == 2  # root, child1


def test_eval_sibling_none(view):
    res = q(view, '{ name = "child1" } ~ { }')
    assert sum(len(s.rows) for s in res) == 0


def test_eval_not_descendant(view):
    res = q(view, '{ name = "root" } !>> { }')
    # falseForAll semantics (ast_execute.go:114): B spans where the relation
    # holds for NO A span — trace2 has no A spans, so all its spans match
    names = {str(n) for s in res for n in view.col("name").values[s.rows]}
    assert names == {"root", "root2", "child2"}


def test_eval_union_descendant(view):
    res = q(view, '{ name = "root" } &>> { name = "leaf" }')
    names = {str(n) for s in res for n in view.col("name").values[s.rows]}
    assert names == {"root", "leaf"}


def test_eval_spanset_and(view):
    res = q(view, '{ name = "root" } && { name = "leaf" }')
    assert sum(len(s.rows) for s in res) == 2
    res = q(view, '{ name = "root" } && { name = "nope" }')
    assert len(res) == 0


def test_eval_spanset_or(view):
    res = q(view, '{ name = "root2" } || { name = "leaf" }')
    assert sum(len(s.rows) for s in res) == 2


def test_eval_count_filter(view):
    res = q(view, "{ } | count() > 2")
    assert len(res) == 1  # only trace1 has 3 spans
    assert res[0].scalars["count()"] == 3.0


def test_eval_avg_duration(view):
    res = q(view, "{ } | avg(duration) > 50ms")
    assert len(res) == 1  # trace1 avg ≈ 56.7ms; trace2 7.5ms


def test_eval_by_group(view):
    res = q(view, "{ } | by(resource.service.name)")
    keys = {s.group_attrs[0][1] for s in res}
    assert keys == {"svc", "other"}


def test_eval_parent_attr(view):
    res = q(view, '{ parent.http.status_code = 200 }')
    # child1's parent (root) has status 200
    names = {str(n) for s in res for n in view.col("name").values[s.rows]}
    assert names == {"child1"}


def test_eval_childcount(view):
    res = q(view, "{ childCount = 1 }")
    assert sum(len(s.rows) for s in res) == 3  # root, child1, root2


def test_eval_root_intrinsics(view):
    res = q(view, '{ rootName = "root2" }')
    assert sum(len(s.rows) for s in res) == 2  # whole trace2
    res = q(view, '{ rootServiceName = "svc" }')
    assert sum(len(s.rows) for s in res) == 3


def test_eval_trace_duration(view):
    res = q(view, "{ traceDuration >= 100ms }")
    assert sum(len(s.rows) for s in res) == 3  # all of trace1


def test_eval_arithmetic(view):
    res = q(view, "{ duration * 2 > 150ms }")
    assert sum(len(s.rows) for s in res) == 1  # root only (100ms*2)


def test_eval_events(view):
    t = make_trace(b"\x03" * 16, [
        (b"f" * 8, b"", "evspan", 10,
         {"events": [{"name": "exception", "time_unix_nano": 1}]}),
    ])
    v = view_from_traces([t])
    res = q(v, '{ event:name = "exception" }')
    assert sum(len(s.rows) for s in res) == 1
    res = q(v, '{ event:name = "other" }')
    assert sum(len(s.rows) for s in res) == 0


# ---------------------------------------------------------------------------
# review regressions
# ---------------------------------------------------------------------------

def test_or_with_empty_arm_matches_everything(tmp_path):
    """'{ .b = 2 } || { }' must match every trace even in hint-mode
    prefiltering (has_unconditioned_arm)."""
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.block.fetch import scan_views
    from tempo_tpu.block.reader import BackendBlock
    from tempo_tpu.block.writer import write_block
    from tempo_tpu.traceql.engine import compile_query, execute_search

    be = LocalBackend(str(tmp_path))
    traces = []
    for i in range(4):
        tid = bytes([i]) * 16
        traces.append((tid, [{
            "trace_id": tid, "span_id": b"\x01" * 8, "name": "s",
            "start_unix_nano": 10 ** 18, "end_unix_nano": 10 ** 18 + 1000,
            "attrs": ({"b": 2} if i == 0 else {}),
        }]))
    meta = write_block(be, "t", traces, row_group_rows=1)
    b = BackendBlock(be, meta)
    q = "{ .b = 2 } || { }"
    _, req = compile_query(q)
    res = execute_search(q, scan_views(b, req), limit=100)
    assert len(res) == 4


def test_dashed_attr_round_trip():
    p = parse('{ span."x-y" = 1 }')
    assert str(parse(str(p))) == str(p)


def test_mixed_type_unscoped_fallback():
    """Span attr foo=5 (num) on one span, resource attr foo='bar' (str) on
    another: '{ .foo = \"bar\" }' must match the resource-only span."""
    t = make_trace(b"\x09" * 16, [
        (b"a" * 8, b"", "s1", 1, {"attrs": {"foo": 5}}),
        (b"b" * 8, b"", "s2", 1, {"res_attrs": {"foo": "bar"}}),
    ])
    v = view_from_traces([t])
    res = q(v, '{ .foo = "bar" }')
    assert sum(len(s.rows) for s in res) == 1
    res = q(v, "{ .foo = 5 }")
    assert sum(len(s.rows) for s in res) == 1
    res = q(v, "{ .foo != nil }")
    assert sum(len(s.rows) for s in res) == 2


def test_mixed_type_row_aligned_compare():
    """MIXED columns must compare row-aligned, not against row 0's rhs:
    { .foo = .bar } with per-row bar values (regression: rv0 bug)."""
    t = make_trace(b"\x0a" * 16, [
        (b"a" * 8, b"", "s1", 1, {"attrs": {"foo": "x", "bar": "x"}}),
        (b"b" * 8, b"", "s2", 1, {"attrs": {"foo": "y", "bar": "z"},
                                  "res_attrs": {}}),
        (b"c" * 8, b"", "s3", 1, {"res_attrs": {"foo": 5}}),
    ])
    v = view_from_traces([t])
    res = q(v, "{ .foo = .bar }")
    assert sum(len(s.rows) for s in res) == 1  # only s1 (x == x)


def test_mixed_type_bool_filter():
    """Bare boolean filter over a MIXED column matches the bool-true rows
    (regression: bool_mask returned all-False for MIXED)."""
    t = make_trace(b"\x0b" * 16, [
        (b"a" * 8, b"", "s1", 1, {"attrs": {"flag": True}}),
        (b"b" * 8, b"", "s2", 1, {"attrs": {"flag": False}}),
        (b"c" * 8, b"", "s3", 1, {"res_attrs": {"flag": "on"}}),
    ])
    v = view_from_traces([t])
    res = q(v, "{ .flag }")
    assert sum(len(s.rows) for s in res) == 1
    res = q(v, "{ .flag = true }")
    assert sum(len(s.rows) for s in res) == 1
    res = q(v, "{ .flag = false }")
    assert sum(len(s.rows) for s in res) == 1


def test_tag_names_populated(view):
    from tempo_tpu.traceql.engine import execute_tag_names

    names = execute_tag_names([(view, np.arange(view.n))])
    assert "http.status_code" in names["span"]
    assert "region" in names["span"]
