"""Mock memcached: a threaded TCP server speaking the text-protocol
subset the client uses (get/set), verifying request shape strictly — the
same signature-checking pattern as mock_s3/mock_kafka: a malformed client
fails the test, not just the lookup."""

from __future__ import annotations

import socketserver
import threading


class MockMemcached:
    def __init__(self) -> None:
        self.store: dict[bytes, bytes] = {}
        self.lock = threading.Lock()
        self.gets = 0
        self.sets = 0
        self.bad_requests = 0

    def start(self):
        mock = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                while True:
                    line = self.rfile.readline()
                    if not line:
                        return
                    line = line.rstrip(b"\r\n")
                    parts = line.split(b" ")
                    if parts[0] == b"get" and len(parts) == 2:
                        mock.gets += 1
                        key = parts[1]
                        if len(key) > 250 or any(
                                c <= 32 or c > 126 for c in key):
                            mock.bad_requests += 1
                            self.wfile.write(b"CLIENT_ERROR bad key\r\n")
                            continue
                        with mock.lock:
                            v = mock.store.get(key)
                        if v is None:
                            self.wfile.write(b"END\r\n")
                        else:
                            self.wfile.write(
                                b"VALUE " + key + b" 0 " +
                                str(len(v)).encode() + b"\r\n" + v +
                                b"\r\nEND\r\n")
                    elif parts[0] == b"set" and len(parts) == 5:
                        mock.sets += 1
                        key, _flags, _exp, n = (parts[1], parts[2],
                                                parts[3], int(parts[4]))
                        data = self.rfile.read(n)
                        self.rfile.read(2)          # \r\n
                        if len(key) > 250 or any(
                                c <= 32 or c > 126 for c in key):
                            mock.bad_requests += 1
                            self.wfile.write(b"CLIENT_ERROR bad key\r\n")
                            continue
                        with mock.lock:
                            mock.store[key] = data
                        self.wfile.write(b"STORED\r\n")
                    else:
                        mock.bad_requests += 1
                        self.wfile.write(b"ERROR\r\n")

        srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), Handler)
        srv.daemon_threads = True
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        return srv, srv.server_address[1]


def start_mock_memcached():
    m = MockMemcached()
    srv, port = m.start()
    return srv, port, m
