"""Mock memcached: a threaded TCP server speaking the text-protocol
subset the client uses (get/set), verifying request shape strictly — the
same signature-checking pattern as mock_s3/mock_kafka: a malformed client
fails the test, not just the lookup."""

from __future__ import annotations

import socketserver
import threading


class MockMemcached:
    def __init__(self) -> None:
        self.store: dict[bytes, bytes] = {}
        self.lock = threading.Lock()
        self.gets = 0
        self.sets = 0
        self.bad_requests = 0

    def start(self):
        mock = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                while True:
                    line = self.rfile.readline()
                    if not line:
                        return
                    line = line.rstrip(b"\r\n")
                    parts = line.split(b" ")
                    if parts[0] == b"get" and len(parts) == 2:
                        mock.gets += 1
                        key = parts[1]
                        if len(key) > 250 or any(
                                c <= 32 or c > 126 for c in key):
                            mock.bad_requests += 1
                            self.wfile.write(b"CLIENT_ERROR bad key\r\n")
                            continue
                        with mock.lock:
                            v = mock.store.get(key)
                        if v is None:
                            self.wfile.write(b"END\r\n")
                        else:
                            self.wfile.write(
                                b"VALUE " + key + b" 0 " +
                                str(len(v)).encode() + b"\r\n" + v +
                                b"\r\nEND\r\n")
                    elif parts[0] == b"set" and len(parts) == 5:
                        mock.sets += 1
                        key, _flags, _exp, n = (parts[1], parts[2],
                                                parts[3], int(parts[4]))
                        data = self.rfile.read(n)
                        self.rfile.read(2)          # \r\n
                        if len(key) > 250 or any(
                                c <= 32 or c > 126 for c in key):
                            mock.bad_requests += 1
                            self.wfile.write(b"CLIENT_ERROR bad key\r\n")
                            continue
                        with mock.lock:
                            mock.store[key] = data
                        self.wfile.write(b"STORED\r\n")
                    else:
                        mock.bad_requests += 1
                        self.wfile.write(b"ERROR\r\n")

        srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), Handler)
        srv.daemon_threads = True
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        return srv, srv.server_address[1]


def start_mock_memcached():
    m = MockMemcached()
    srv, port = m.start()
    return srv, port, m


class MockRedis:
    """RESP2 GET/SET subset with strict framing verification."""

    def __init__(self) -> None:
        self.store: dict[bytes, bytes] = {}
        self.lock = threading.Lock()
        self.gets = 0
        self.sets = 0

    def start(self):
        mock = self

        class Handler(socketserver.StreamRequestHandler):
            def _arg(self):
                hdr = self.rfile.readline().rstrip(b"\r\n")
                assert hdr[:1] == b"$", hdr
                n = int(hdr[1:])
                v = self.rfile.read(n)
                self.rfile.read(2)
                return v

            def handle(self):
                while True:
                    line = self.rfile.readline()
                    if not line:
                        return
                    line = line.rstrip(b"\r\n")
                    assert line[:1] == b"*", line
                    argc = int(line[1:])
                    args = [self._arg() for _ in range(argc)]
                    cmd = args[0].upper()
                    if cmd == b"GET" and argc == 2:
                        mock.gets += 1
                        with mock.lock:
                            v = mock.store.get(args[1])
                        if v is None:
                            self.wfile.write(b"$-1\r\n")
                        else:
                            self.wfile.write(
                                b"$" + str(len(v)).encode() + b"\r\n" +
                                v + b"\r\n")
                    elif cmd == b"SET" and argc in (3, 5):
                        if argc == 5:
                            assert args[3].upper() == b"EX", args
                            int(args[4])
                        mock.sets += 1
                        with mock.lock:
                            mock.store[args[1]] = args[2]
                        self.wfile.write(b"+OK\r\n")
                    else:
                        self.wfile.write(b"-ERR unknown command\r\n")

        srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), Handler)
        srv.daemon_threads = True
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        return srv, srv.server_address[1]


def start_mock_redis():
    m = MockRedis()
    srv, port = m.start()
    return srv, port, m
