"""Paged ragged device state (registry/pages.py + ops/pages.py):
page-table registry/sketch planes vs the dense fixed-capacity layout.

The contract under test: with the page pool on, every family and the
spanmetrics fused path produce BIT-identical collect()/quantile()
output to the dense layout — across push/purge/evict interleavings,
across the direct / scheduler-coalesced / serving-mesh routes, and
across series shard counts {1,2,4} — while allocating only the pages
active series actually touch. Exhaustion degrades to series discards
(the spent-budget analog), never to wrong numbers.
"""

from __future__ import annotations

import numpy as np
import pytest

from tempo_tpu.registry import pages as P
from tempo_tpu.registry.registry import ManagedRegistry, RegistryOverrides


def _pool(page_rows=16, arena_slots=512):
    return P.PagePool(P.PagePoolConfig(enabled=True, page_rows=page_rows,
                                       arena_slots=arena_slots))


def _registry(pool, cap=64, now=None, tenant="t"):
    with P.use(pool):
        return ManagedRegistry(
            tenant, RegistryOverrides(max_active_series=cap,
                                      stale_duration_s=100.0),
            now=now or (lambda: 1000.0))


def _collect_exact(reg, ts=5000) -> list:
    return sorted((s.name, s.labels, s.value) for s in reg.collect(ts)
                  if s.value == s.value)  # NaN stale markers compare by count


# -- pool mechanics ----------------------------------------------------------

def test_pages_allocate_on_demand_and_free_on_purge():
    t = [1000.0]
    pool = _pool()
    reg = _registry(pool, now=lambda: t[0])
    c = reg.new_counter("c_total", ("svc",))
    assert pool.allocated_total == 0
    c.inc(["a"])
    assert pool.allocated_total == 1
    assert c.table.active_count == 1
    # same page serves the whole slot range it covers
    c.inc(["b"])
    assert pool.allocated_total == 1
    assert pool.tenant_bytes()["t"] == pool.cfg.page_rows * 4
    # idle out both series: the page returns to the free list
    t[0] += 1000
    reg.purge_stale()
    assert pool.evicted_total == 1
    assert pool.free_pages() == pool.total_pages()
    assert pool.tenant_bytes() == {}


def test_page_reuse_starts_from_zero():
    t = [1000.0]
    pool = _pool()
    reg = _registry(pool, now=lambda: t[0])
    c = reg.new_counter("c_total", ("svc",))
    c.inc(["a"], 7.0)
    t[0] += 1000
    reg.purge_stale()
    # the freed physical page is re-handed to a NEW series; its rows
    # must read zero, not the evicted tenant's 7.0
    c.inc(["z"], 1.0)
    vals = {s.labels: s.value for s in reg.collect(1)
            if not s.is_stale_marker}
    assert list(vals.values()) == [1.0]


def test_pool_exhaustion_discards_like_spent_budget():
    pool = _pool(page_rows=16, arena_slots=16)  # exactly one page/kind
    reg = _registry(pool, cap=64)
    c = reg.new_counter("c_total", ("svc",))
    rows = reg.interner.intern_many(
        [f"s{i}" for i in range(32)])[:, None]
    slots = c.inc_batch(rows, np.ones(32, np.float32))
    # first 16 slots fit the single page; the rest were refused
    assert (slots >= 0).sum() == 16
    assert c.table.discarded == 16
    assert pool.alloc_failures > 0
    # existing series keep updating after exhaustion
    before = c._snap()[0][slots[0]]
    c.inc_batch(rows[:1], np.ones(1, np.float32))
    assert c._snap()[0][slots[0]] == before + 1.0


def test_backing_all_or_nothing_across_planes():
    # a histogram series needs pages in THREE role arenas (buckets,
    # sums, counts). Exhaust the sums arena via a same-named family in
    # another tenant registry (arenas are shared per role), then
    # allocate a series in this one: it must be refused entirely — the
    # buckets/counts arenas keep their pages, nothing is stranded
    pool = _pool(page_rows=16, arena_slots=16)  # one page per role arena
    other = _registry(pool, cap=16, tenant="hog")
    other.new_histogram("h", ("svc",)).observe(["x"], 0.1)
    reg = _registry(pool, cap=16)
    h = reg.new_histogram("h", ("svc",))
    h.observe(["b"], 0.5)
    assert h.table.discarded == 1
    assert pool.alloc_failures > 0
    wide = pool.arena("float32", len(h.hist_edges()) + 1, "h/buckets")
    assert len(wide.free) == 0          # the hog's page, not a stranded one
    assert wide.owners.count("hog") == 1
    assert "t" not in pool.tenant_bytes()


def test_config_check_bounds():
    assert P.PagePoolConfig(page_rows=48).check()          # non-pow2
    assert P.PagePoolConfig(page_rows=64, arena_slots=32).check()
    assert not P.PagePoolConfig().check()
    # capacity-indivisible page sizes are refused with a clear error
    msgs = P.PagePoolConfig(page_rows=256).check(capacities=(1000,))
    assert any("capacity-indivisible" in m for m in msgs)
    msgs = P.PagePoolConfig(arena_slots=4096).check(capacities=(65536,))
    assert any("below the largest single-tenant capacity" in m for m in msgs)


def test_app_config_check_surfaces_pages_problems():
    from tempo_tpu.app.config import load_config
    cfg = load_config(text="""
pages: {enabled: true, page_rows: 48}
""")
    assert any("pages:" in w for w in cfg.check())
    # and a clean block stays quiet
    cfg = load_config(text="""
pages: {enabled: true, page_rows: 256, arena_slots: 131072}
""")
    assert not [w for w in cfg.check() if "pages:" in w]


def test_configure_refuses_bad_config_gracefully():
    assert P.configure(P.PagePoolConfig(enabled=True, page_rows=48)) is None
    assert P.active() is None
    pool = P.configure(P.PagePoolConfig(enabled=True, page_rows=16,
                                        arena_slots=256))
    assert pool is not None and P.active() is pool
    P.reset()


def test_indivisible_tenant_falls_back_dense():
    pool = _pool(page_rows=16)
    with P.use(pool):
        reg = ManagedRegistry(
            "odd", RegistryOverrides(max_active_series=100))  # 100 % 16 != 0
        assert reg.pages is None
        c = reg.new_counter("c_total", ("svc",))
        assert not hasattr(c, "planes")  # dense family


# -- family bit-identity -----------------------------------------------------

def _drive_families(reg, t):
    rng = np.random.default_rng(7)
    c = reg.new_counter("c_total", ("svc",))
    g = reg.new_gauge("g", ("svc",))
    h = reg.new_histogram("h", ("svc",))
    nh = reg.new_native_histogram("nh", ("svc",))
    outs = []
    for round_ in range(3):
        for _ in range(4):
            rows = reg.interner.intern_many(
                [f"s{j}" for j in rng.integers(0, 9, 32)])[:, None]
            c.inc_batch(rows, rng.random(32).astype(np.float32))
            g.set_batch(rows, rng.random(32).astype(np.float32))
            h.observe_batch(rows, (rng.random(32) * 3).astype(np.float32))
            nh.observe_batch(rows, (rng.random(32) * 3).astype(np.float32))
        outs.append(_collect_exact(reg, ts=round_))
        payload = nh.native_payload()
        outs.append([(np.asarray(x).tolist() if hasattr(x, "shape") else x)
                     for x in payload[2:]])
        t[0] += 1000
        reg.purge_stale()   # evict EVERYTHING, then the next round reuses
    return outs


def test_families_bit_identical_paged_vs_dense_with_eviction():
    t1, t2 = [1000.0], [1000.0]
    paged = _drive_families(_registry(_pool(), now=lambda: t1[0]), t1)
    dense = _drive_families(
        ManagedRegistry("t", RegistryOverrides(max_active_series=64,
                                               stale_duration_s=100.0),
                        now=lambda: t2[0]), t2)
    assert paged == dense


# -- spanmetrics routes ------------------------------------------------------

def _mk_proc(paged, cap=512, use_sched=False, page_rows=64,
             arena_slots=4096, sketch_max=256):
    from tempo_tpu.generator.processors.spanmetrics import (
        SpanMetricsConfig, SpanMetricsProcessor)

    pool = _pool(page_rows, arena_slots) if paged else None
    t = [1000.0]
    with P.use(pool):
        reg = ManagedRegistry("t",
                              RegistryOverrides(max_active_series=cap,
                                                stale_duration_s=100.0),
                              now=lambda: t[0])
        proc = SpanMetricsProcessor(reg, SpanMetricsConfig(
            use_scheduler=use_sched, sketch_max_series=sketch_max))
    return reg, proc, t, pool


def _batch(reg, seed, n=1500):
    from tempo_tpu.model.span_batch import SpanBatchBuilder

    b = SpanBatchBuilder(reg.interner)
    r = np.random.default_rng(seed)
    for i in range(n):
        b.append(trace_id=r.bytes(16), span_id=r.bytes(8),
                 name=f"op-{i % 9}", service=f"svc-{i % 3}",
                 kind=int(i % 6), status_code=int(i % 3),
                 start_unix_nano=10**18,
                 end_unix_nano=10**18 + int(r.lognormal(16, 1.0)))
    return b.build()


def _run_proc(paged, use_sched=False, purge=True):
    from tempo_tpu import sched

    reg, proc, t, _pool_ = _mk_proc(paged, use_sched=use_sched)
    sc = sched.DeviceScheduler() if use_sched else None
    if sc is not None:
        sc.start()
    with (sched.use(sc) if sc is not None else _null()):
        for seed in (1, 2):
            proc.push_batch(_batch(reg, seed))
        if purge:
            if sc is not None:
                sc.flush()
            t[0] += 1000
            reg.purge_stale()       # evict-then-reuse the same pages
            t0 = t[0]
            del t0
            for seed in (3, 4):
                proc.push_batch(_batch(reg, seed))
        if sc is not None:
            sc.flush()
        out = _collect_exact(reg)
        qq = proc.quantile(0.99)
    if sc is not None:
        sc.stop()
    return out, qq


def _null():
    import contextlib
    return contextlib.nullcontext()


def test_spanmetrics_paged_direct_bit_identical():
    assert _run_proc(True) == _run_proc(False)


def test_spanmetrics_paged_sched_bit_identical():
    assert _run_proc(True, use_sched=True) == _run_proc(False)


def test_sketch_plane_prefix_masked_like_dense():
    # sketch_max_series < capacity: slots past the plane must have no
    # quantile in either layout (the paged plane rounds its page cover
    # up but masks at the CONFIGURED row count)
    rp, pp, _, _ = _mk_proc(True, cap=512, sketch_max=96, page_rows=64)
    rd, pd, _, _ = _mk_proc(False, cap=512, sketch_max=96)
    for seed in (1, 2, 3):
        pp.push_batch(_batch(rp, seed))
        pd.push_batch(_batch(rd, seed))
    assert pp.quantile(0.5) == pd.quantile(0.5)
    assert _collect_exact(rp) == _collect_exact(rd)


def test_servicegraphs_paged_bit_identical():
    from tempo_tpu.generator.processors.servicegraphs import (
        ServiceGraphsConfig, ServiceGraphsProcessor)

    def run(paged):
        pool = _pool(page_rows=16, arena_slots=512) if paged else None
        with P.use(pool):
            reg = ManagedRegistry(
                "t", RegistryOverrides(max_active_series=64),
                now=lambda: 1000.0)
            proc = ServiceGraphsProcessor(reg, ServiceGraphsConfig())
        proc.push_batch(_sg_batch(reg))
        return _collect_exact(reg)

    assert run(True) == run(False)


def _sg_batch(reg, n=200):
    from tempo_tpu.model.span_batch import SpanBatchBuilder

    b = SpanBatchBuilder(reg.interner)
    r = np.random.default_rng(3)
    for i in range(n):
        tid = r.bytes(16)
        parent = r.bytes(8)
        start = 10**18
        b.append(trace_id=tid, span_id=parent, name="cli",
                 service=f"svc-{i % 3}", kind=3, status_code=int(i % 2),
                 start_unix_nano=start, end_unix_nano=start + 5_000_000)
        b.append(trace_id=tid, span_id=r.bytes(8), parent_span_id=parent,
                 name="srv", service=f"svc-{(i + 1) % 3}", kind=2,
                 status_code=0, start_unix_nano=start + 1_000_000,
                 end_unix_nano=start + 4_000_000)
    return b.build()


# -- serving-mesh composition ------------------------------------------------

@pytest.mark.skipif("len(__import__('jax').devices()) < 4",
                    reason="needs 4 virtual devices")
def test_paged_collect_bit_identical_across_series_shards():
    """Arenas shard page-aligned over 'series'; each shard scatters the
    same rows in order into the pages it owns — collect() and the
    sketch quantile must be bit-identical at shards {1,2,4} AND equal
    to the dense single-device answer."""
    from tempo_tpu.parallel import serving

    dense = _run_proc(False)
    outs = {}
    for shards in (1, 2, 4):
        sm = serving.ServingMesh(serving.MeshConfig(
            enabled=True, devices=shards, series_shards=shards))
        with serving.use(sm):
            outs[shards] = _run_proc(True)
        assert P.active() is None
    assert outs[1] == outs[2] == outs[4] == dense


@pytest.mark.skipif("len(__import__('jax').devices()) < 4",
                    reason="needs 4 virtual devices")
def test_pool_on_data_parallel_mesh_stays_single_device():
    from tempo_tpu.parallel import serving

    sm = serving.ServingMesh(serving.MeshConfig(
        enabled=True, devices=4, series_shards=2))  # data axis = 2
    with serving.use(sm):
        pool = _pool()
        assert pool.mesh is None      # warned, arenas single-device
        reg, proc, _, _ = _mk_proc(False)
    del reg, proc


# -- zero steady-state recompiles across tenants -----------------------------

def test_many_tenants_share_one_trace():
    """2k-tenant scaling rests on this: tenant #2's dispatch must hit
    tenant #1's compiled step (page tables are operands, the static
    meta is config-derived)."""
    from tempo_tpu.obs.jaxruntime import JIT_COMPILES

    pool = _pool(page_rows=64, arena_slots=4096)
    with P.use(pool):
        regs = []
        procs = []
        from tempo_tpu.generator.processors.spanmetrics import (
            SpanMetricsConfig, SpanMetricsProcessor)
        for i in range(4):
            r = ManagedRegistry(f"t{i}",
                                RegistryOverrides(max_active_series=512),
                                now=lambda: 1000.0)
            procs.append(SpanMetricsProcessor(
                r, SpanMetricsConfig(use_scheduler=False,
                                     sketch_max_series=256)))
            regs.append(r)
        procs[0].push_batch(_batch(regs[0], 1))  # warm the step
        before = JIT_COMPILES.value(("spanmetrics_fused_update",))
        for i in range(1, 4):
            procs[i].push_batch(_batch(regs[i], 1))
        after = JIT_COMPILES.value(("spanmetrics_fused_update",))
    assert after == before, "per-tenant dispatch retraced the fused step"


# -- paged sketch kernels (HLL / log2) ---------------------------------------

def test_paged_hll_and_log2_match_dense_sketches():
    import jax.numpy as jnp

    from tempo_tpu.ops import pages as op
    from tempo_tpu.ops import sketches

    rng = np.random.default_rng(11)
    n, n_series, page_rows = 256, 32, 8
    sids = rng.integers(0, n_series, n).astype(np.int32)
    h1 = rng.integers(0, 1 << 32, n, dtype=np.uint32)
    h2 = rng.integers(1, 1 << 32, n, dtype=np.uint32)
    vals = rng.lognormal(0, 2, n).astype(np.float32)
    w = np.ones(n, np.float32)
    shift = page_rows.bit_length() - 1

    # identity page table: logical page i -> physical page i
    table = np.arange(n_series // page_rows, dtype=np.int32)

    hll_d = sketches.hll_update(sketches.hll_init(n_series, precision=6),
                                sids, h1, h2)
    ar = jnp.zeros((n_series, 1 << 6), jnp.int32)
    ar = op.hll_step(6, shift)(ar, table, sids, h1, h2)
    np.testing.assert_array_equal(np.asarray(hll_d.registers),
                                  np.asarray(ar))

    lg_d = sketches.log2_hist_update(
        sketches.log2_hist_init(n_series, offset=32), sids, vals, weights=w)
    ah = jnp.zeros((n_series, 64), jnp.float32)
    ah = op.log2_hist_step(32, shift)(ah, table, sids, vals, w)
    np.testing.assert_array_equal(np.asarray(lg_d.counts), np.asarray(ah))

    # standalone paged DDSketch step (the fused path has its own inline
    # dd scatter; this keeps the bare builder honest too)
    dd_d = sketches.dd_update(
        sketches.dd_init(n_series, rel_err=0.02, min_value=1e-6,
                         max_value=1e3), sids, vals, weights=w)
    az, ad = op.dd_step(dd_d.gamma, dd_d.min_value, shift)(
        jnp.zeros((n_series,), jnp.float32),
        jnp.zeros(dd_d.counts.shape, jnp.float32), table, table,
        sids, vals, w)
    np.testing.assert_array_equal(np.asarray(dd_d.counts), np.asarray(ad))
    np.testing.assert_array_equal(np.asarray(dd_d.zeros), np.asarray(az))


# -- obs / status surfaces ---------------------------------------------------

def test_pool_status_and_obs_families_render():
    from tempo_tpu.obs.jaxruntime import RUNTIME

    pool = _pool()
    with P.use(pool):
        reg = ManagedRegistry(
            "t9", RegistryOverrides(max_active_series=64),
            now=lambda: 1000.0)
        c = reg.new_counter("c_total", ("svc",))
        c.inc(["a"])
        st = pool.status()
        assert st["allocated_total"] == 1
        # status reports USABLE pages: every arena reserves physical
        # page 0 as the pallas kernel's trash page
        assert st["arenas"][0]["pages"] == pool._arena_pages - 1
        assert st["arenas"][0]["reserved"] == 1
        assert st["top_tenant_bytes"][0]["tenant"] == "t9"
        text = RUNTIME.render()
        assert "tempo_pages_free" in text
        assert "tempo_pages_allocated_total 1" in text


def test_registry_state_bytes_gauge_and_status():
    from tempo_tpu.generator.generator import Generator
    from tempo_tpu.generator.instance import GeneratorConfig
    from tempo_tpu.obs.registry import Registry

    span = {"trace_id": b"\x01" * 16, "span_id": b"\x02" * 8,
            "name": "op", "service": "svc", "kind": 2, "status_code": 0,
            "start_unix_nano": 10**18, "end_unix_nano": 10**18 + 10**6}

    def mk_cfg():
        cfg = GeneratorConfig(processors=("span-metrics",))
        cfg.registry.max_active_series = 128
        cfg.spanmetrics.sketch_max_series = 64
        return cfg

    pool = _pool(page_rows=16, arena_slots=1024)
    with P.use(pool):
        obs = Registry()
        gen = Generator(mk_cfg(), registry=obs, now=lambda: 1e9)
        gen.push_spans("acme", [span])
        inst = gen.instances["acme"]
        assert inst.state_layout == "paged"
        paged_bytes = inst.device_state_bytes()
        assert 0 < paged_bytes < 10 * (1 << 20)
        text = obs.render()
        assert 'tempo_registry_state_bytes{' in text and \
            'layout="paged"' in text
    # dense comparison: same tenant shape costs the full pre-sized planes
    gen_d = Generator(mk_cfg(), registry=Registry(), now=lambda: 1e9)
    gen_d.push_spans("acme", [span])
    dense_bytes = gen_d.instances["acme"].device_state_bytes()
    assert gen_d.instances["acme"].state_layout == "dense"
    assert dense_bytes >= 4 * paged_bytes


# -- full App integration ----------------------------------------------------

def test_app_serves_paged_layout_end_to_end(tmp_path):
    """target=all App with `pages.enabled`: OTLP over HTTP lands in
    paged state through the production distributor→sched→generator
    path, /status exposes the pool + per-tenant bytes, /metrics renders
    the page families."""
    import json
    import socket
    import urllib.request

    from tempo_tpu.app import App
    from tempo_tpu.app.api import serve
    from tempo_tpu.app.config import Config

    cfg = Config()
    cfg.storage.backend = "mem"
    cfg.storage.wal_path = str(tmp_path / "wal")
    cfg.generator.localblocks.data_dir = str(tmp_path / "lb")
    cfg.generator.registry.max_active_series = 4096
    cfg.generator.spanmetrics.sketch_max_series = 1024
    cfg.pages.enabled = True
    cfg.pages.page_rows = 64
    cfg.pages.arena_slots = 4096
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        cfg.server.http_listen_port = s.getsockname()[1]
    assert not [w for w in cfg.check() if "pages:" in w]
    app = App(cfg)
    app.overrides.set_tenant_patch("single-tenant", {
        "generator": {"processors": ["span-metrics"]}})
    try:
        assert app.pages is not None
        srv = serve(app, block=False)
        base = f"http://127.0.0.1:{cfg.server.http_listen_port}"
        import time as _time
        t0 = int(_time.time() * 1e9)   # inside the ingestion slack window
        otlp = json.dumps({"resourceSpans": [{
            "resource": {"attributes": [
                {"key": "service.name",
                 "value": {"stringValue": "shop"}}]},
            "scopeSpans": [{"spans": [{
                "traceId": "0102030405060708090a0b0c0d0e0f10",
                "spanId": "0102030405060708", "name": "checkout",
                "kind": 3, "startTimeUnixNano": str(t0),
                "endTimeUnixNano": str(t0 + 5 * 10**6)}]}]}]}).encode()
        req = urllib.request.Request(
            base + "/v1/traces", data=otlp,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
        from tempo_tpu import sched
        sched.flush()
        with urllib.request.urlopen(base + "/status", timeout=10) as r:
            st = json.loads(r.read())
        assert st["pages"] is not None
        assert st["pages"]["allocated_total"] >= 1
        layouts = {v["layout"] for v in st["registry_state_bytes"].values()}
        assert layouts == {"paged"}
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            text = r.read().decode()
        assert "tempo_pages_allocated_total" in text
        assert 'tempo_registry_state_bytes{' in text
        srv.shutdown()
    finally:
        app.shutdown()
