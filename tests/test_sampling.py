"""Graceful-overload sampling: determinism, the pressure controller,
Horvitz-Thompson weights, the staged-path wiring, and the satellite
regressions (limiter bucket eviction, remote-write retry behavior)."""

from __future__ import annotations

import time

import numpy as np
import pytest

from tempo_tpu import native, sched
from tempo_tpu.distributor.sampler import (SpanSampler, _DurationSketch,
                                           trace_hash_u01)
from tempo_tpu.overrides.limits import SamplingLimits
from tempo_tpu.sched import SchedConfig, fraction_for_pressure


def _recs(n: int, seed: int = 0, err_every: int = 0,
          dur_ns: int = 1_000_000, tail_every: int = 0,
          tail_dur_ns: int = 10_000_000_000) -> np.ndarray:
    """Synthetic StageRec rows: distinct trace ids, optional error and
    latency-tail stripes."""
    rng = np.random.default_rng(seed)
    recs = np.zeros(n, native.STAGE_REC_DTYPE)
    recs["trace_id"] = rng.integers(0, 256, (n, 16), dtype=np.uint8)
    recs["tid_len"] = 16
    recs["start_ns"] = 1_000_000_000
    recs["end_ns"] = 1_000_000_000 + dur_ns
    if err_every:
        recs["status_code"][::err_every] = 2
    if tail_every:
        recs["end_ns"][1::tail_every] = 1_000_000_000 + tail_dur_ns
    return recs


def _policy(**kw) -> SamplingLimits:
    # tail disarmed by default: most tests want the pure-hash decision
    base = dict(tail_min_spans=1 << 30)
    base.update(kw)
    return SamplingLimits(**base)


# -- the deterministic hash ------------------------------------------------


def test_trace_hash_pure_function_of_id_bytes():
    recs = _recs(512, seed=1)
    u1 = trace_hash_u01(recs["trace_id"])
    u2 = trace_hash_u01(recs["trace_id"].copy())
    assert np.array_equal(u1, u2)
    # order invariance: the variate belongs to the ID, not the row
    perm = np.random.default_rng(2).permutation(512)
    assert np.array_equal(trace_hash_u01(recs["trace_id"][perm]), u1[perm])


def test_trace_hash_roughly_uniform():
    tids = np.random.default_rng(3).integers(0, 256, (200_000, 16),
                                             dtype=np.uint8)
    u = trace_hash_u01(tids)
    assert 0.49 < u.mean() < 0.51
    for f in (0.1, 0.25, 0.5):
        assert abs((u < f).mean() - f) < 0.01


def test_keep_monotone_in_fraction():
    """Raising the keep-fraction only ADDS spans — the property that
    makes a moving controller stable (a trace never flaps out)."""
    recs = _recs(4096, seed=4)
    valid = np.ones(4096, bool)
    pol = _policy(keep_errors=False)
    s = SpanSampler(fraction_source=lambda: 0.5)
    k_lo, _ = s.sample("t", recs, valid, 0.2, pol)
    k_hi, _ = s.sample("t", recs, valid, 0.6, pol)
    assert not (k_lo & ~k_hi).any()


def test_sampler_decisions_agree_across_replicas():
    """keep/drop is a pure function of (trace id, policy): two fresh
    sampler instances — think two distributor replicas, or a replayed
    retry — make identical decisions for identical inputs."""
    recs = _recs(2048, seed=5, err_every=7)
    valid = np.ones(2048, bool)
    pol = _policy()
    ka, wa = SpanSampler().sample("a", recs, valid, 0.3, pol)
    kb, wb = SpanSampler().sample("b", recs, valid, 0.3, pol)
    assert np.array_equal(ka, kb)
    assert np.array_equal(wa, wb)
    # same trace id appearing in a different payload: same decision
    recs2 = np.concatenate([recs[1024:], recs[:1024]])
    kc, _ = SpanSampler().sample("c", recs2, valid, 0.3, pol)
    assert np.array_equal(kc, np.concatenate([ka[1024:], ka[:1024]]))


# -- forced keeps and weights ----------------------------------------------


def test_error_spans_always_kept_exactly():
    recs = _recs(1000, seed=6, err_every=5)
    valid = np.ones(1000, bool)
    keep, w = SpanSampler().sample("t", recs, valid, 0.01, _policy())
    errs = recs["status_code"] == 2
    assert keep[errs].all()
    assert np.allclose(w[errs], 1.0)     # exact, never upscaled


def test_latency_tail_always_kept_once_armed():
    recs = _recs(2000, seed=7, tail_every=100)
    valid = np.ones(2000, bool)
    pol = _policy(tail_min_spans=100, tail_quantile=0.99, keep_errors=False)
    s = SpanSampler()
    for _ in range(5):
        s.observe("t", recs)             # warm the duration sketch
    keep, w = s.sample("t", recs, valid, 0.01, pol)
    tail = recs["end_ns"].astype(np.int64) - recs["start_ns"].astype(np.int64)
    tail = tail > 1_000_000_000          # the 10s stripe
    assert keep[tail].all()
    assert np.allclose(w[tail], 1.0)


def test_horvitz_thompson_weights_recover_true_rate():
    recs = _recs(40_000, seed=8, err_every=10)
    valid = np.ones(len(recs), bool)
    frac = 0.25
    keep, w = SpanSampler().sample("t", recs, valid, frac, _policy())
    est = float(w[keep].sum())
    assert abs(est - len(recs)) / len(recs) < 0.02
    # hash-kept spans carry exactly 1/frac
    hash_kept = keep & (recs["status_code"] != 2)
    assert np.allclose(w[hash_kept], 1.0 / frac)


def test_duration_sketch_quantile():
    sk = _DurationSketch()
    durs = np.concatenate([np.full(9900, 0.01), np.full(100, 10.0)])
    sk.record(durs)
    q99 = sk.quantile(0.99)
    assert 0.005 < q99 < 0.05            # p99 sits in the body's bucket
    assert sk.quantile(0.999) > 1.0      # p99.9 reaches the 10s stripe
    # out-of-range q from a misconfigured tenant policy must clamp, not
    # crash the push path
    assert sk.quantile(1.5) == sk.quantile(1.0)
    assert sk.quantile(-0.5) == sk.quantile(0.0)


# -- the pressure controller -----------------------------------------------


def test_fraction_for_pressure_control_law():
    assert fraction_for_pressure(0.0, 0.5, 0.05) == 1.0
    assert fraction_for_pressure(0.5, 0.5, 0.05) == 1.0
    assert fraction_for_pressure(1.0, 0.5, 0.05) == pytest.approx(0.05)
    mid = fraction_for_pressure(0.75, 0.5, 0.05)
    assert 0.05 < mid < 1.0
    # monotone non-increasing in pressure
    fs = [fraction_for_pressure(p, 0.5, 0.05)
          for p in np.linspace(0, 1.2, 25)]
    assert all(a >= b for a, b in zip(fs, fs[1:]))


def test_scheduler_keep_fraction_tracks_pressure(forced_sched_saturation):
    sc = forced_sched_saturation(0.0)
    assert sc.keep_fraction() == 1.0                 # exactly off
    assert sched.ingest_keep_fraction() == 1.0
    sc.forced_pressure = 0.8
    f = sched.ingest_keep_fraction()
    assert 0.05 <= f < 1.0
    sc.forced_pressure = 0.0
    assert sched.ingest_keep_fraction() == 1.0       # snaps fully off


def test_keep_fraction_smoothing_ramps_and_snaps_back(
        forced_sched_saturation):
    t = [0.0]
    sc = forced_sched_saturation(0.0, SchedConfig(sampling_smoothing_s=1.0))
    sc.now = lambda: t[0]
    assert sc.keep_fraction() == 1.0
    sc.forced_pressure = 1.0
    t[0] += 0.1
    f1 = sc.keep_fraction()
    assert f1 > sc.cfg.sampling_min_fraction        # ramping, not a step
    t[0] += 30.0
    f2 = sc.keep_fraction()
    assert f2 == pytest.approx(sc.cfg.sampling_min_fraction, abs=1e-6)
    sc.forced_pressure = 0.0
    t[0] += 30.0
    assert sc.keep_fraction() == 1.0                 # exact recovery


def test_control_pressure_includes_inflight_jobs():
    """The controller's pressure must not collapse to zero while the
    worker chews a popped backlog — in-flight ingest jobs count."""
    from tempo_tpu.sched import DeviceScheduler

    sc = DeviceScheduler(SchedConfig(max_queue_ingest=10,
                                     sampling_smoothing_s=0.0),
                         start_worker=False)
    mid_dispatch: list[float] = []

    def dispatch(arr):
        mid_dispatch.append(sc.control_pressure())

    for _ in range(4):
        sc.submit_rows("k", "mk", (np.zeros(2, np.float32),), 2, dispatch)
    assert sc.control_pressure() == pytest.approx(0.4)
    sc.drain_once(force=True)
    # during the dispatch the queue was empty but 4 jobs were in flight
    assert mid_dispatch and mid_dispatch[0] == pytest.approx(0.4)
    assert sc.control_pressure() == 0.0


def test_effective_fraction_floor_and_optout():
    s = SpanSampler(fraction_source=lambda: 0.1)
    assert s.effective_fraction("t", _policy(floor=0.4)) == 0.4
    assert s.effective_fraction("t", _policy(floor=0.0)) == \
        pytest.approx(0.1)
    assert s.effective_fraction("t", _policy(enabled=False)) == 1.0
    s2 = SpanSampler(fraction_source=lambda: 1.0)
    assert s2.effective_fraction("t", _policy(floor=0.4)) == 1.0


def test_sampler_idle_tenant_eviction():
    t = [0.0]
    s = SpanSampler(now=lambda: t[0])
    for i in range(50):
        s.observe(f"ten-{i}", _recs(4, seed=i))
    assert s.tenants() == 50
    t[0] = SpanSampler.IDLE_TTL_S + 1.0
    s._next_sweep = 0.0
    s.observe("fresh", _recs(4))
    assert s.tenants() == 1


# -- satellite: rate-limiter bucket eviction --------------------------------


def test_rate_limiter_buckets_bounded_under_tenant_churn():
    from tempo_tpu.distributor.limiter import RateLimiter

    t = [0.0]
    rl = RateLimiter(now=lambda: t[0], idle_ttl_s=60.0, max_buckets=100)
    for i in range(5000):
        t[0] += 0.001
        rl.allow(f"churn-{i}", 10, 1000.0, 1000.0)
    assert len(rl._buckets) <= 100 + 1   # max-size trim holds under churn
    # TTL pass: idle buckets vanish, an active one survives
    t[0] += 30.0
    rl.allow("keepalive", 10, 1000.0, 1000.0)
    t[0] += 45.0                          # idle > 60s for the churn set
    rl._next_sweep = 0.0
    rl.allow("keepalive", 10, 1000.0, 1000.0)
    assert set(rl._buckets) == {"keepalive"}


def test_rate_limiter_churn_cannot_launder_spent_burst():
    """An attacker churning ephemeral tenant ids must not force the trim
    to evict a DRAINED bucket (recreation would regrant a full burst):
    refilled buckets are evicted first, unrefilled ones survive."""
    from tempo_tpu.distributor.limiter import RateLimiter

    t = [0.0]
    rl = RateLimiter(now=lambda: t[0], idle_ttl_s=1e6, max_buckets=50)
    # tenant A drains its whole burst at a trickle refill rate
    assert rl.allow("A", 1000, 1.0, 1000.0)
    # churn: fast-refill ephemeral tenants blow past the cap repeatedly
    for i in range(500):
        t[0] += 0.01
        rl.allow(f"churn-{i}", 1, 1e6, 1000.0)
    # A's bucket was the oldest, but unrefilled → survived every trim
    t[0] += 1.0
    assert not rl.allow("A", 1000, 1.0, 1000.0)


def test_rate_limiter_eviction_is_lossless():
    """An evicted-idle bucket refills to burst anyway: recreation admits
    exactly what a kept bucket would have."""
    from tempo_tpu.distributor.limiter import RateLimiter

    t = [0.0]
    kept = RateLimiter(now=lambda: t[0], idle_ttl_s=1e9)
    evicted = RateLimiter(now=lambda: t[0], idle_ttl_s=10.0)
    for rl in (kept, evicted):
        assert rl.allow("t", 900, 100.0, 1000.0)
    t[0] = 20.0
    evicted._next_sweep = 0.0
    evicted.allow("other", 1, 100.0, 1000.0)   # triggers the sweep
    assert "t" not in evicted._buckets
    for rl in (kept, evicted):
        assert rl.allow("t", 1000, 100.0, 1000.0)   # both refilled to burst
        assert not rl.allow("t", 500, 100.0, 1000.0)


# -- satellite: remote-write retry behavior ---------------------------------


def test_remote_write_honors_retry_after(faulty_remote_write):
    from tempo_tpu.generator.remote_write import (RemoteWriteClient,
                                                  RemoteWriteConfig)
    from tempo_tpu.registry.series import Sample

    srv = faulty_remote_write
    srv.script.append((429, {"Retry-After": "0.05"}))
    c = RemoteWriteClient(RemoteWriteConfig(url=srv.url, retries=2,
                                            backoff_s=0.01))
    sleeps: list[float] = []
    c._sleep = sleeps.append
    ok = c.send([Sample(name="m", labels=(("a", "b"),), value=1.0, ts_ms=0)])
    assert ok
    assert len(srv.requests) == 2
    assert c.retried_sends == 1 and c.failed_sends == 0
    assert sleeps and sleeps[0] >= 0.05        # Retry-After is the floor


def test_remote_write_full_jitter_backoff(faulty_remote_write):
    """Without Retry-After, sleeps are U(0, base·2^attempt): bounded
    above by the exponential envelope and not all identical (the
    anti-synchronization property)."""
    import random

    from tempo_tpu.generator.remote_write import (RemoteWriteClient,
                                                  RemoteWriteConfig)
    from tempo_tpu.registry.series import Sample

    srv = faulty_remote_write
    for _ in range(3):
        srv.script.append((503, {}))
    c = RemoteWriteClient(RemoteWriteConfig(url=srv.url, retries=3,
                                            backoff_s=0.5))
    c._rng = random.Random(42)
    sleeps: list[float] = []
    c._sleep = sleeps.append
    ok = c.send([Sample(name="m", labels=(("a", "b"),), value=1.0, ts_ms=0)])
    assert ok and len(sleeps) == 3
    for i, s in enumerate(sleeps):
        assert 0.0 <= s <= 0.5 * (2 ** i)
    assert len({round(s, 6) for s in sleeps}) > 1


def test_remote_write_non_retryable_4xx_fails_fast(faulty_remote_write):
    from tempo_tpu.generator.remote_write import (RemoteWriteClient,
                                                  RemoteWriteConfig)
    from tempo_tpu.registry.series import Sample

    srv = faulty_remote_write
    srv.script.append((400, {}))
    c = RemoteWriteClient(RemoteWriteConfig(url=srv.url, retries=3,
                                            backoff_s=0.01))
    c._sleep = lambda s: None
    ok = c.send([Sample(name="m", labels=(("a", "b"),), value=1.0, ts_ms=0)])
    assert not ok
    assert len(srv.requests) == 1             # no retry on a client error
    assert c.failed_sends == 1 and c.retried_sends == 0


def test_remote_write_obs_families_register():
    from tempo_tpu.obs.jaxruntime import RUNTIME
    import tempo_tpu.generator.remote_write  # noqa: F401 — registers

    text = RUNTIME.render()
    for fam in ("tempo_remote_write_retries_total",
                "tempo_remote_write_sends_total",
                "tempo_remote_write_failed_sends_total"):
        assert fam in text
