"""Randomized device-vs-host parity gate for the DEFAULT read path.

Round-4 weak #6: the fused device plane is the default product path, but
its parity evidence was a fixed query list. This property test draws
random TraceQL queries from the AST grammar (filters over every column
family the plane adopts — int/float/string/missing attrs, intrinsics,
boundary literals, nil/boolean forms, OR-fallback shapes — times every
metrics kind and group-by arity) against randomized blocks, asserting the
device plane and the host engine agree on BOTH search results and metric
grids. The seed is printed on failure and can be pinned via
TEMPO_FUZZ_SEED; case count via TEMPO_FUZZ_CASES (default sized to keep
the whole module under a minute in CI).
"""

from __future__ import annotations

import math
import os
import random

import numpy as np
import pytest

from tempo_tpu.backend.mem import MemBackend
from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig
from tempo_tpu.traceql.engine_metrics import QueryRangeRequest

T0 = 1_700_000_000
SEED = int(os.environ.get("TEMPO_FUZZ_SEED",
                          random.SystemRandom().randrange(1 << 30)))
N_QUERIES = int(os.environ.get("TEMPO_FUZZ_CASES", 40))

# -- random query grammar ----------------------------------------------------

_DUR_LITS = ["1ns", "50ms", "123ms", "16777216ns", "16777217ns", "1s", "2s"]
_NUM_OPS = ["=", "!=", ">", ">=", "<", "<="]
_STR_OPS = ["=", "!=", "=~", "!~"]


def _pred(rng: random.Random) -> str:
    kind = rng.choice(["dur", "name", "svc", "int_attr", "float_attr",
                       "str_attr", "missing", "kindp", "status", "nil",
                       "bool_lit"])
    if kind == "dur":
        return f"duration {rng.choice(_NUM_OPS)} {rng.choice(_DUR_LITS)}"
    if kind == "name":
        return (f'name {rng.choice(_STR_OPS)} '
                f'"op-{rng.randrange(6)}{rng.choice(["", ".*"])}"')
    if kind == "svc":
        return (f'resource.service.name {rng.choice(["=", "!="])} '
                f'"svc-{rng.randrange(4)}"')
    if kind == "int_attr":
        lit = rng.choice([200, 204, 350, 499, 500, 0, -1])
        return f"span.http.status_code {rng.choice(_NUM_OPS)} {lit}"
    if kind == "float_attr":
        lit = rng.choice([0.5, 1.5, -2.25, 0.0, 3.0, 2, 0.1])
        return f"span.ratio {rng.choice(_NUM_OPS)} {lit}"
    if kind == "str_attr":
        return f'span.region {rng.choice(_STR_OPS)} "r{rng.randrange(3)}"'
    if kind == "missing":
        return f"span.nothere {rng.choice(_NUM_OPS)} 5"
    if kind == "kindp":
        return f'kind = {rng.choice(["server", "client", "internal"])}'
    if kind == "status":
        return f'status {rng.choice(["=", "!="])} error'
    if kind == "nil":
        attr = rng.choice(["span.ratio", "span.region", "span.nothere"])
        return f'{attr} {rng.choice(["=", "!="])} nil'
    return rng.choice(["true", "false"])


def _filter(rng: random.Random) -> str:
    n = rng.choice([0, 1, 1, 2, 2, 3])
    if n == 0:
        return "{ }"
    if n >= 3 and rng.random() < 0.3:
        # mixed AND/OR trees: NOT pure disjunctions — the fused plane must
        # refuse these (a superset mask would silently corrupt metrics;
        # the round-5 review found exactly this via crafted dedup shapes)
        a, b, c = (_pred(rng) for _ in range(3))
        return rng.choice([f"{{ {a} && ({b} || {c}) }}",
                           f"{{ ({a} && {b}) || {c} }}",
                           f"{{ {a} || ({a} && {b}) }}"])
    op = " && " if rng.random() < 0.7 else " || "
    return "{ " + op.join(_pred(rng) for _ in range(n)) + " }"


def _metrics(rng: random.Random) -> str:
    # 3-key arity exercises the composed mixed-radix group codes
    by_keys = rng.sample(["resource.service.name", "name", "span.region",
                          "kind"], k=rng.choice([0, 1, 1, 2, 3]))
    by = f" by ({', '.join(by_keys)})" if by_keys else ""
    agg = rng.choice(["rate()", "count_over_time()",
                      "min_over_time(duration)", "max_over_time(duration)",
                      "sum_over_time(duration)", "avg_over_time(duration)",
                      "sum_over_time(span.http.status_code)",
                      "avg_over_time(span.ratio)",
                      "quantile_over_time(duration, .5, .99)",
                      "histogram_over_time(duration)"])
    return f"{_filter(rng)} | {agg}{by}"


# -- random block ------------------------------------------------------------

@pytest.fixture(scope="module")
def fuzz_dbs():
    rng = np.random.default_rng(SEED)
    be = MemBackend()
    dev = TempoDB(be, be, TempoDBConfig(device_plane=True))
    host = TempoDB(be, be, TempoDBConfig(device_plane=False))
    n_blocks = 2
    for b in range(n_blocks):
        traces = []
        for i in range(1500):
            tid = rng.bytes(16)
            start = int((T0 + b * 400 + float(rng.random()) * 390) * 1e9)
            attrs = {}
            if rng.random() < 0.8:
                attrs["http.status_code"] = int(rng.integers(200, 501))
            if rng.random() < 0.6:
                attrs["ratio"] = float(rng.choice(
                    [0.5, 1.5, -2.25, 0.0, 3.0, 0.1, 2.0]))
            if rng.random() < 0.7:
                attrs["region"] = f"r{int(rng.integers(0, 3))}"
            traces.append((tid, [{
                "trace_id": tid, "span_id": rng.bytes(8),
                "name": f"op-{int(rng.integers(0, 6))}",
                "service": f"svc-{int(rng.integers(0, 4))}",
                "kind": int(rng.integers(0, 6)),
                "status_code": int(rng.integers(0, 3)),
                "start_unix_nano": start,
                "end_unix_nano": start + int(rng.choice(
                    [1, 50_000_000, 123_000_000, 16_777_216, 16_777_217,
                     int(rng.lognormal(16, 1.5))])),
                "attrs": attrs}]))
        traces.sort(key=lambda t: t[0])
        dev.write_block("t", traces, replication_factor=1)
    dev.poll_now()
    host.poll_now()
    return dev, host


def _smap(series) -> dict:
    return {tuple(sorted((str(k), str(v)) for k, v in s.labels)):
            np.nan_to_num(np.asarray(s.samples, np.float64))
            for s in series}


def test_fuzz_query_range_parity(fuzz_dbs):
    dev, host = fuzz_dbs
    rng = random.Random(SEED)
    for case in range(N_QUERIES):
        q = _metrics(rng)
        # random windows: offset starts exercise the q_steps/frac split of
        # the exact bucketing, sub-windows exercise the clip terms
        w0 = T0 + rng.choice([0, -120, 37, 333, 701])
        w1 = w0 + rng.choice([900, 301, 1500, 83])
        req = QueryRangeRequest(query=q, start_ns=int(w0 * 1e9),
                                end_ns=int(w1 * 1e9),
                                step_ns=int(rng.choice([30, 60, 300, 7])
                                            * 1e9))
        ctx = f"seed={SEED} case={case} query={q!r}"
        try:
            a = _smap(dev.query_range("t", req))
            b = _smap(host.query_range("t", req))
        except Exception as e:
            raise AssertionError(f"{ctx}: {e}") from e
        assert set(a) == set(b), f"{ctx}: series sets differ " \
            f"(only-dev={set(a) - set(b)}, only-host={set(b) - set(a)})"
        for k in b:
            np.testing.assert_allclose(a[k], b[k], rtol=1e-5, atol=1e-4,
                                       err_msg=f"{ctx} series={k}")


def test_fuzz_moments_tier_query_range_parity(fuzz_dbs):
    """The warm-read differential arm: the SAME random grammar (kind ×
    by-arity × predicates) under the moments query tier. Gates: count
    kinds stay bit-identical between the fused plane and the host
    engine; quantile series (solved off the moment rows both ways) stay
    inside the tier's error envelope; and the run must actually ride
    the moments grids (fused blocks move)."""
    from tempo_tpu.ops import moments as M

    dev, host = fuzz_dbs
    rng = random.Random(SEED + 11)
    fused0 = dev.plane_stats.get("fused_metric_blocks", 0)
    quantile_fused = 0
    # case 0 is pinned: a fused-eligible quantile shape, so the
    # rode-the-moments-grid assertion below cannot depend on the draw
    pinned = ("{ } | quantile_over_time(duration, .5, .99)"
              " by (resource.service.name)")
    with M.use_query_tier("moments"):
        for case in range(max(N_QUERIES // 4, 8)):
            q = pinned if case == 0 else _metrics(rng)
            w0 = T0 + rng.choice([0, -120, 37, 333])
            w1 = w0 + rng.choice([900, 301, 1500])
            req = QueryRangeRequest(query=q, start_ns=int(w0 * 1e9),
                                    end_ns=int(w1 * 1e9),
                                    step_ns=int(rng.choice([30, 60, 300])
                                                * 1e9))
            ctx = f"seed={SEED} case={case} query={q!r} tier=moments"
            f0 = dev.plane_stats.get("fused_metric_blocks", 0)
            try:
                a = _smap(dev.query_range("t", req))
                b = _smap(host.query_range("t", req))
            except Exception as e:
                raise AssertionError(f"{ctx}: {e}") from e
            if "quantile_over_time" in q:
                quantile_fused += (
                    dev.plane_stats.get("fused_metric_blocks", 0) - f0)
            assert set(a) == set(b), f"{ctx}: series sets differ " \
                f"(only-dev={set(a) - set(b)}, only-host={set(b) - set(a)})"
            for k in b:
                if "quantile_over_time" in q:
                    # moments error gate: both sides solve the maxent
                    # problem off independently-accumulated f32 moment
                    # sums — reduction order differs, the answer class
                    # (tier bound) must not
                    np.testing.assert_allclose(
                        a[k], b[k], rtol=5e-2, atol=1e-6,
                        err_msg=f"{ctx} series={k}")
                elif ("rate()" in q or "count_over_time" in q
                      or "histogram_over_time" in q):
                    # count kinds: integer grid cells → bit-identical
                    assert np.array_equal(a[k], b[k]), \
                        f"{ctx} series={k}: count-kind series not " \
                        f"bit-identical ({a[k]} vs {b[k]})"
                else:
                    # float-sum kinds carry f32 reduction-order noise
                    np.testing.assert_allclose(
                        a[k], b[k], rtol=1e-5, atol=1e-4,
                        err_msg=f"{ctx} series={k}")
    assert dev.plane_stats.get("fused_metric_blocks", 0) > fused0, \
        f"seed={SEED}: moments-tier run never rode the fused plane"
    assert quantile_fused > 0, \
        f"seed={SEED}: no quantile_over_time block rode the moments grid"


def test_forced_refusal_exercises_batched_fallback(fuzz_dbs):
    """≥1 deterministic refusal: a mixed AND/OR filter is NOT fusable
    (superset masks would corrupt metrics), so the block must route to
    the batched host fallback — the cause counter moves, the batched
    evaluator answers, and parity against the host-only instance still
    holds bit-for-bit."""
    dev, host = fuzz_dbs
    q = ('{ name = "op-1" && (resource.service.name = "svc-0" '
         '|| span.region = "r1") } | rate() by (name)')
    req = QueryRangeRequest(query=q, start_ns=int(T0 * 1e9),
                            end_ns=int((T0 + 900) * 1e9),
                            step_ns=int(60 * 1e9))
    before = dict(dev.plane_stats)
    a = _smap(dev.query_range("t", req))
    b = _smap(host.query_range("t", req))
    cause_delta = (dev.plane_stats.get("fallback_query_shape", 0)
                   - before.get("fallback_query_shape", 0))
    host_delta = (dev.plane_stats.get("host_metric_blocks", 0)
                  - before.get("host_metric_blocks", 0))
    assert cause_delta > 0 and host_delta > 0, \
        f"refusal did not route to the host fallback: {dev.plane_stats}"
    assert set(a) == set(b)
    for k in b:
        assert np.array_equal(a[k], b[k]), f"series={k}"


def test_zero_steady_state_recompiles_read_paths(fuzz_dbs):
    """Warm repeats of BOTH warm-read paths — the fused moments grid and
    the batched host fallback — must reuse their compiled traces: zero
    jit compiles across the steady-state phase (the ISSUE 20 acceptance
    gate, over the product entry point)."""
    from tempo_tpu.obs.jaxruntime import JIT_COMPILES
    from tempo_tpu.ops import moments as M

    dev, _host = fuzz_dbs
    fused_q = ("{ } | quantile_over_time(duration, .5, .99)"
               " by (resource.service.name)")
    refusal_q = ('{ name = "op-1" && (resource.service.name = "svc-0" '
                 '|| span.region = "r1") } | rate() by (name)')
    reqs = [QueryRangeRequest(query=q, start_ns=int(T0 * 1e9),
                              end_ns=int((T0 + 900) * 1e9),
                              step_ns=int(60 * 1e9))
            for q in (fused_q, refusal_q)]

    def total_compiles():
        with JIT_COMPILES._lock:
            return sum(JIT_COMPILES._series.values())

    with M.use_query_tier("moments"):
        for _ in range(2):                      # warm every shape bucket
            for req in reqs:
                dev.query_range("t", req)
        warm = total_compiles()
        for _ in range(3):
            for req in reqs:
                dev.query_range("t", req)
        assert total_compiles() == warm, \
            "steady-state repeats recompiled a read-path kernel"


def test_fuzz_search_parity(fuzz_dbs):
    dev, host = fuzz_dbs
    rng = random.Random(SEED + 1)
    for case in range(N_QUERIES):
        q = _filter(rng)
        ctx = f"seed={SEED} case={case} query={q!r}"
        try:
            a = sorted(m.trace_id for m in dev.search("t", q, limit=5000))
            b = sorted(m.trace_id for m in host.search("t", q, limit=5000))
        except Exception as e:
            raise AssertionError(f"{ctx}: {e}") from e
        assert a == b, f"{ctx}: {len(a)} dev vs {len(b)} host trace ids"


# -- paged-vs-dense differential arm -----------------------------------------
#
# The write-plane twin of the read-path parity gate above: random
# push/purge/collect/quantile interleavings across 3 tenants must be
# BIT-identical between the paged layout (registry/pages.py page-table
# arenas) and the dense fixed-capacity layout — including full-eviction
# rounds that free pages and the immediate reuse of the same physical
# pages (the free list is LIFO) by other tenants' new series.

def _pv_make_world(paged: bool):
    from tempo_tpu.generator.processors.spanmetrics import (
        SpanMetricsConfig, SpanMetricsProcessor)
    from tempo_tpu.registry import pages as device_pages
    from tempo_tpu.registry.registry import ManagedRegistry, RegistryOverrides

    clock = [1000.0]
    pool = device_pages.PagePool(device_pages.PagePoolConfig(
        enabled=True, page_rows=16, arena_slots=1024)) if paged else None
    tenants = {}
    with device_pages.use(pool):
        for t in ("a", "b", "c"):
            reg = ManagedRegistry(
                t, RegistryOverrides(max_active_series=64,
                                     stale_duration_s=50.0),
                now=lambda: clock[0])
            proc = SpanMetricsProcessor(reg, SpanMetricsConfig(
                use_scheduler=False, sketch_max_series=32))
            tenants[t] = (reg, proc)
    return clock, tenants, pool


def _pv_batch(reg, rng: random.Random, n: int):
    from tempo_tpu.model.span_batch import SpanBatchBuilder

    b = SpanBatchBuilder(reg.interner)
    for _ in range(n):
        b.append(trace_id=rng.getrandbits(128).to_bytes(16, "big"),
                 span_id=rng.getrandbits(64).to_bytes(8, "big"),
                 name=f"op-{rng.randrange(12)}",
                 service=f"svc-{rng.randrange(4)}",
                 kind=rng.randrange(6), status_code=rng.randrange(3),
                 start_unix_nano=10**18,
                 end_unix_nano=10**18 + rng.randrange(1, 10**9))
    return b.build()


def test_fuzz_paged_vs_dense_differential():
    n_ops = int(os.environ.get("TEMPO_FUZZ_CASES", 40))
    worlds = [_pv_make_world(paged) for paged in (True, False)]
    script = random.Random(SEED + 2)
    tenant_names = ("a", "b", "c")
    for step in range(n_ops):
        op = script.choice(["push", "push", "push", "purge", "collect",
                            "quantile", "idle"])
        t = script.choice(tenant_names)
        seed = script.randrange(1 << 30)
        n = script.choice([17, 64, 256])
        dt = script.choice([0.0, 5.0, 60.0])   # 60s+ steps age series out
        ctx = f"seed={SEED} step={step} op={op} tenant={t}"
        results = []
        for clock, tenants, _pool in worlds:
            reg, proc = tenants[t]
            rng = random.Random(seed)
            clock[0] += dt
            if op == "push":
                proc.push_batch(_pv_batch(reg, rng, n))
                results.append(reg.budget.used)
            elif op == "purge":
                results.append(reg.purge_stale())
            elif op == "collect":
                results.append(sorted(
                    (s.name, s.labels, s.value)
                    for s in reg.collect(step) if s.value == s.value))
            elif op == "quantile":
                results.append(proc.quantile(
                    rng.choice([0.5, 0.9, 0.99])))
            else:
                results.append(None)
        assert results[0] == results[1], ctx
    # deterministic coda (random scripts may not evict): age EVERY
    # series out, purge, and repopulate — the paged world must recycle
    # the just-freed physical pages (LIFO free list) for the new series
    for clock, tenants, _pool in worlds:
        clock[0] += 1000.0
        for t in tenant_names:
            tenants[t][0].purge_stale()
        rng = random.Random(SEED + 3)
        for t in tenant_names:
            tenants[t][1].push_batch(_pv_batch(tenants[t][0], rng, 64))
    # closing audit: every tenant's full state agrees bit-for-bit, and
    # the paged world actually exercised eviction + page reuse
    for t in tenant_names:
        outs = [sorted((s.name, s.labels, s.value)
                       for s in w[1][t][0].collect(10**6)
                       if s.value == s.value) for w in worlds]
        qq = [w[1][t][1].quantile(0.99) for w in worlds]
        assert outs[0] == outs[1], f"seed={SEED} tenant={t} final collect"
        assert qq[0] == qq[1], f"seed={SEED} tenant={t} final quantile"
    pool = worlds[0][2]
    assert pool.allocated_total > pool.total_pages() - pool.free_pages(), \
        f"seed={SEED}: fuzz script never recycled a page (weak run)"


# -- moments-vs-exact differential arm ---------------------------------------
#
# The quantile-accuracy twin of the paged-vs-dense arm: random WEIGHTED
# op scripts (pushes with Horvitz-Thompson-style weights, purges, and
# evict-then-reuse of slots) against moments-tier processors in BOTH
# layouts. Gates: (1) paged and dense moments worlds stay bit-identical,
# (2) every live series' quantile answers stay inside the tier's error
# bound versus an exactly-tracked weighted distribution — including
# series whose slot was recycled after a purge (stale history leaking
# into a reused row is exactly what this arm would catch), and (3) the
# solver never falls back in steady state.

# -- pallas-vs-xla kernel-tier differential arm -------------------------------
#
# ISSUE 11's write-plane gate: random op scripts (pushes with integer
# HT weights, purges, evict-then-reuse) through the Pallas ragged-page
# kernel (interpret mode — CPU containers cannot lower Mosaic) vs the
# composed-scatter path, both over the PAGED layout. Contract (module
# docstring of ops/pallas_kernels.py): integer-count planes and the
# DDSketch quantile are BIT-identical under integer weights; float-sum
# planes agree to f32 reduction-order tolerance. A third world runs the
# int32/bf16-pair compact tier against a dense f32 reference and must
# stay inside the tier's documented tolerances.

def _kt_make_world(kernel: str, compact: bool = False, paged: bool = True):
    from tempo_tpu.generator.processors.spanmetrics import (
        SpanMetricsConfig, SpanMetricsProcessor)
    from tempo_tpu.registry import pages as device_pages
    from tempo_tpu.registry.registry import ManagedRegistry, RegistryOverrides

    clock = [1000.0]
    pool = device_pages.PagePool(device_pages.PagePoolConfig(
        enabled=True, page_rows=16, arena_slots=1024)) if paged else None
    with device_pages.use(pool):
        reg = ManagedRegistry(
            "k", RegistryOverrides(max_active_series=64,
                                   stale_duration_s=50.0),
            now=lambda: clock[0])
        proc = SpanMetricsProcessor(reg, SpanMetricsConfig(
            use_scheduler=False, sketch_max_series=32, sketch_rel_err=0.05,
            kernel=kernel, pallas_interpret=(kernel == "pallas"),
            compact_state=compact))
    return clock, reg, proc


_SUM_SUFFIXES = ("_sum", "_size_total")


def _kt_compare(a, b, ctx, *, count_exact=True, count_abs=0.0,
                sum_rtol=1e-6):
    """Collect-sample comparison under the kernel-tier numerics
    contract: count-family samples exact (or within `count_abs` for the
    compact rounding tier), sum-family samples within `sum_rtol`."""
    assert len(a) == len(b), ctx
    for (na, la, va), (nb, lb, vb) in zip(a, b):
        assert (na, la) == (nb, lb), f"{ctx}: series sets differ"
        if na.endswith(_SUM_SUFFIXES):
            assert abs(va - vb) <= sum_rtol * max(abs(va), 1e-9) + 1e-9, \
                f"{ctx}: {na}{la} sum {va} vs {vb}"
        elif count_exact:
            assert va == vb, f"{ctx}: {na}{la} count {va} vs {vb}"
        else:
            assert abs(va - vb) <= count_abs, \
                f"{ctx}: {na}{la} count {va} vs {vb} (tol {count_abs})"


def test_fuzz_pallas_vs_xla_differential():
    n_ops = max(int(os.environ.get("TEMPO_FUZZ_CASES", 40)) // 3, 10)
    worlds = [_kt_make_world(k) for k in ("pallas", "xla")]
    script = random.Random(SEED + 6)
    for step in range(n_ops):
        op = script.choice(["push", "push", "push", "purge", "collect",
                            "quantile"])
        seed = script.randrange(1 << 30)
        n = script.choice([17, 64])
        dt = script.choice([0.0, 5.0, 60.0])
        weighted = script.random() < 0.5
        ctx = f"seed={SEED} step={step} op={op}"
        results = []
        for clock, reg, proc in worlds:
            rng = random.Random(seed)
            clock[0] += dt
            if op == "push":
                wts = (np.random.default_rng(seed).integers(1, 4, n)
                       .astype(np.float32) if weighted else None)
                proc.push_batch(_pv_batch(reg, rng, n),
                                sample_weights=wts)
                results.append(reg.budget.used)
            elif op == "purge":
                results.append(reg.purge_stale())
            elif op == "collect":
                results.append(sorted(
                    (s.name, s.labels, s.value)
                    for s in reg.collect(step) if s.value == s.value))
            else:
                # DDSketch quantile rides integer-exact grid counts →
                # bit-identical between kernel tiers
                results.append(proc.quantile(rng.choice([0.5, 0.99])))
        if op == "collect":
            _kt_compare(results[0], results[1], ctx)
        else:
            assert results[0] == results[1], ctx
    # deterministic evict-reuse coda (same shape as the paged-vs-dense
    # arm): age everything out, repurge, repopulate — the pallas world's
    # freed pages must recycle identically
    for clock, reg, proc in worlds:
        clock[0] += 1000.0
        reg.purge_stale()
        proc.push_batch(_pv_batch(reg, random.Random(SEED + 7), 64))
    finals = [sorted((s.name, s.labels, s.value)
                     for s in w[1].collect(10**6) if s.value == s.value)
              for w in worlds]
    _kt_compare(finals[0], finals[1], f"seed={SEED} final")
    qq = [w[2].quantile(0.99) for w in worlds]
    assert qq[0] == qq[1], f"seed={SEED} final quantile"


def test_fuzz_compact_tier_within_tolerance():
    """int32/bf16-pair state (pallas interpret) vs a dense f32 reference:
    integer-weight pushes keep every count plane exact; a fractional-
    weight push stays inside the ±0.5-per-dispatch rounding envelope;
    bf16 Kahan sums hold 1% relative."""
    script = random.Random(SEED + 8)
    compact = _kt_make_world("pallas", compact=True)
    ref = _kt_make_world("xla", paged=False)
    frac_pushes = 0
    n_pushes = 8
    for step in range(n_pushes):
        seed = script.randrange(1 << 30)
        n = script.choice([17, 64])
        fractional = step in (3, 6)
        frac_pushes += fractional
        for clock, reg, proc in (compact, ref):
            rng = random.Random(seed)
            wrng = np.random.default_rng(seed)
            wts = (wrng.uniform(0.5, 2.5, n).astype(np.float32)
                   if fractional
                   else wrng.integers(1, 4, n).astype(np.float32))
            proc.push_batch(_pv_batch(reg, rng, n), sample_weights=wts)
    outs = [sorted((s.name, s.labels, s.value)
                   for s in w[1].collect(1) if s.value == s.value)
            for w in (compact, ref)]
    # each fractional dispatch can shift a touched cell by ≤0.5
    _kt_compare(outs[0], outs[1], f"seed={SEED} compact",
                count_exact=False, count_abs=0.5 * frac_pushes + 1e-6,
                sum_rtol=0.01)
    # dd quantiles come off the (rounding-tolerance) int32 grid: compare
    # against the reference within the sketch's own relative error class
    qa = compact[2].quantile(0.99)
    qb = ref[2].quantile(0.99)
    assert set(qa) == set(qb)
    for k, va in qa.items():
        assert abs(va - qb[k]) <= 0.15 * max(abs(qb[k]), 1e-9) + 1e-9, \
            f"seed={SEED} {k}: {va} vs {qb[k]}"


# -- trace-analytics structural-plane differential arm ------------------------
#
# The write-plane gate for the structural tier (critical-path seconds,
# error root-cause counts, latency-share moments): random
# push/cut/purge/collect/quantile scripts across randomized trace DAGs
# must be BIT-identical (1) between the paged and dense layouts and
# (2) between the direct dispatch route and the device-scheduler route
# (one coalesced job per plane per cut) — including evict rounds that
# zero share-sketch rows and the immediate reuse of freed pages/slots.

def _ta_make_world(paged: bool, use_sched: bool):
    from tempo_tpu.generator.processors.traceanalytics import (
        TraceAnalyticsConfig, TraceAnalyticsProcessor)
    from tempo_tpu.registry import pages as device_pages
    from tempo_tpu.registry.registry import ManagedRegistry, RegistryOverrides

    clock = [1000.0]
    pool = device_pages.PagePool(device_pages.PagePoolConfig(
        enabled=True, page_rows=16, arena_slots=1024)) if paged else None
    with device_pages.use(pool):
        reg = ManagedRegistry(
            "ta", RegistryOverrides(max_active_series=64,
                                    stale_duration_s=50.0),
            now=lambda: clock[0])
        proc = TraceAnalyticsProcessor(reg, TraceAnalyticsConfig(
            trace_idle_s=1.0, use_scheduler=use_sched,
            sketch_max_series=32))
    return clock, reg, proc


def _ta_batch(reg, rng: random.Random, n_traces: int):
    from tempo_tpu.model.span_batch import SpanBatchBuilder

    b = SpanBatchBuilder(reg.interner)
    for _ in range(n_traces):
        tid = rng.getrandbits(128).to_bytes(16, "big")
        sids = [rng.getrandbits(64).to_bytes(8, "big")
                for _ in range(rng.randrange(2, 7))]
        t0 = 10**18
        for i, sid in enumerate(sids):
            par = b"" if i == 0 else sids[rng.randrange(0, i)]
            if rng.random() < 0.05:          # orphan pointer
                par = rng.getrandbits(64).to_bytes(8, "big")
            b.append(trace_id=tid, span_id=sid, parent_span_id=par,
                     name=f"op-{rng.randrange(8)}",
                     service=f"svc-{rng.randrange(4)}",
                     status_code=2 if rng.random() < 0.3 else 0,
                     start_unix_nano=t0 + i,
                     end_unix_nano=t0 + rng.randrange(10**6, 10**9))
    return b.build()


def test_fuzz_traceanalytics_paged_sched_differential():
    from tempo_tpu import sched
    from tempo_tpu.sched.scheduler import SchedConfig

    sched.configure(SchedConfig(batch_window_ms=0.0))
    n_ops = max(int(os.environ.get("TEMPO_FUZZ_CASES", 40)) // 2, 15)
    # three worlds, two axes: paged-vs-dense (direct route) and
    # direct-vs-scheduler (paged layout)
    worlds = [_ta_make_world(paged=True, use_sched=False),
              _ta_make_world(paged=False, use_sched=False),
              _ta_make_world(paged=True, use_sched=True)]
    script = random.Random(SEED + 9)
    for step in range(n_ops):
        op = script.choice(["push", "push", "cut", "cut", "purge",
                            "collect", "quantile", "idle"])
        seed = script.randrange(1 << 30)
        nt = script.choice([3, 8, 20])
        dt = script.choice([0.0, 2.0, 60.0])
        # drawn ONCE per step, not per world — a per-world draw can hand
        # the three worlds different flags and diverge them spuriously
        immediate = script.random() < 0.5
        ctx = f"seed={SEED} step={step} op={op}"
        results = []
        for clock, reg, proc in worlds:
            rng = random.Random(seed)
            clock[0] += dt
            if op == "push":
                proc.push_batch(_ta_batch(reg, rng, nt))
                results.append(proc.spans_buffered)
            elif op == "cut":
                proc.cut_tick(immediate=immediate)
                sched.flush()
                results.append(len(proc._live))
            elif op == "purge":
                sched.flush()       # in-flight adds land before eviction
                results.append(reg.purge_stale())
            elif op == "collect":
                sched.flush()
                results.append(sorted(
                    (s.name, s.labels, s.value)
                    for s in reg.collect(step) if s.value == s.value))
            elif op == "quantile":
                results.append(proc.quantile(rng.choice([0.5, 0.9])))
            else:
                results.append(None)
        assert results[0] == results[1] == results[2], ctx
    # deterministic evict-reuse coda: cut and age out EVERYTHING, purge
    # (zeroing the share rows of every evicted slot), then repopulate —
    # the paged worlds recycle freed physical pages, the dense world
    # reuses slots; answers must reflect ONLY the new stream
    for clock, reg, proc in worlds:
        proc.cut_tick(immediate=True)
        sched.flush()
        clock[0] += 1000.0
        reg.purge_stale()
        proc.push_batch(_ta_batch(reg, random.Random(SEED + 10), 12))
        proc.cut_tick(immediate=True)
        sched.flush()
    finals = [sorted((s.name, s.labels, s.value)
                     for s in w[1].collect(10**6) if s.value == s.value)
              for w in worlds]
    assert finals[0] == finals[1] == finals[2], f"seed={SEED} final collect"
    qq = [w[2].quantile(0.9) for w in worlds]
    assert qq[0] == qq[1] == qq[2], f"seed={SEED} final quantile"
    assert qq[0], f"seed={SEED}: coda produced no share-quantile series"


def _mx_make_world(paged: bool):
    from tempo_tpu.generator.processors.spanmetrics import (
        SpanMetricsConfig, SpanMetricsProcessor)
    from tempo_tpu.registry import pages as device_pages
    from tempo_tpu.registry.registry import ManagedRegistry, RegistryOverrides

    clock = [1000.0]
    pool = device_pages.PagePool(device_pages.PagePoolConfig(
        enabled=True, page_rows=16, arena_slots=512)) if paged else None
    with device_pages.use(pool):
        reg = ManagedRegistry(
            "m", RegistryOverrides(max_active_series=64,
                                   stale_duration_s=50.0),
            now=lambda: clock[0])
        proc = SpanMetricsProcessor(reg, SpanMetricsConfig(
            use_scheduler=False, sketch="moments", sketch_max_series=32))
    return clock, reg, proc


def _mx_weighted_quantile(samples: list, q: float) -> float:
    vals = np.array([v for v, _ in samples])
    wts = np.array([w for _, w in samples])
    order = np.argsort(vals)
    cum = np.cumsum(wts[order])
    i = int(np.searchsorted(cum, q * cum[-1], side="left"))
    return float(vals[order][min(i, len(vals) - 1)])


def test_fuzz_moments_vs_exact_differential():
    from tempo_tpu.model.span_batch import SpanBatchBuilder
    from tempo_tpu.ops import moments as M

    n_ops = max(int(os.environ.get("TEMPO_FUZZ_CASES", 40)) // 2, 12)
    script = random.Random(SEED + 4)
    worlds = [_mx_make_world(paged) for paged in (True, False)]
    exact: dict[str, list] = {}       # op name -> [(duration, weight)]
    fb0 = M.fallbacks_total

    def check():
        for q in (0.5, 0.99):
            per_world = [w[2].quantile(q) for w in worlds]
            assert per_world[0] == per_world[1], \
                f"seed={SEED} q={q}: paged != dense"
            for labels, est in per_world[0].items():
                op = dict(labels)["span_name"]
                samples = exact.get(op)
                if not samples or len(samples) < 16:
                    continue
                ex = _mx_weighted_quantile(samples, q)
                vals = np.sort(np.array([v for v, _ in samples]))
                rel = abs(est - ex) / max(ex, 1e-12)
                rank = abs(np.searchsorted(vals, est) / len(vals) - q)
                # tier bound at volume; sampling-noise slack below it
                # (the empirical quantile of a 100-point multi-scale
                # mixture is itself ~1/sqrt(n) uncertain, and a median
                # falling BETWEEN scale clusters is noisy in both the
                # estimate and the oracle — seed 59571098 misses a
                # 2.0/sqrt(n) slack by 1% on exactly that shape).
                # Corruption — stale history in a reused slot,
                # cross-layout drift — shows up as GROSS error either
                # way.
                tol = max(0.08, 2.5 / math.sqrt(len(samples)))
                assert min(rel, rank) <= tol, \
                    f"seed={SEED} op={op} q={q}: est={est} exact={ex}"

    for step in range(n_ops):
        op = script.choice(["push", "push", "push", "purge", "check",
                            "idle"])
        seed = script.randrange(1 << 30)
        dt = script.choice([0.0, 5.0, 60.0])
        for clock, reg, proc in worlds:
            clock[0] += dt
        if op == "push":
            rng = np.random.default_rng(seed)
            name = f"op-{script.randrange(6)}"
            n = script.choice([32, 64, 128])
            scale = script.choice([0.01, 0.1, 1.0])
            durs = rng.lognormal(np.log(scale), 0.7, n)
            wts = (rng.integers(1, 4, n).astype(np.float32)
                   if script.random() < 0.5 else np.ones(n, np.float32))
            exact.setdefault(name, []).extend(zip(durs.tolist(),
                                                  wts.tolist()))
            for clock, reg, proc in worlds:
                b = SpanBatchBuilder(reg.interner)
                for d in durs:
                    b.append(trace_id=bytes(16), span_id=bytes(8),
                             name=name, service="svc", kind=2,
                             status_code=0, start_unix_nano=10**18,
                             end_unix_nano=10**18 + int(d * 1e9))
                proc.push_batch(b.build(), sample_weights=wts)
        elif op == "purge":
            evicted = [w[1].purge_stale() for w in worlds]
            assert evicted[0] == evicted[1], f"seed={SEED} step={step}"
            if evicted[0]:
                # drop exact tracking for the ops that aged out (their
                # device rows were zeroed; a re-push starts both fresh)
                proc = worlds[0][2]
                live = {dict(proc.calls.labels_of(int(s)))["span_name"]
                        for s in proc.calls.table.active_slots()}
                for name in list(exact):
                    if name not in live:
                        del exact[name]
        elif op == "check":
            check()
    # deterministic evict-reuse coda: age everything out, repopulate the
    # SAME op names (paged world recycles freed pages, dense reuses
    # slots) — answers must reflect ONLY the new stream
    for clock, reg, proc in worlds:
        clock[0] += 1000.0
        assert reg.purge_stale() >= 0
    exact.clear()
    rng = np.random.default_rng(SEED + 5)
    durs = rng.lognormal(np.log(0.02), 0.4, 128)
    exact["op-0"] = [(d, 1.0) for d in durs.tolist()]
    for clock, reg, proc in worlds:
        b = SpanBatchBuilder(reg.interner)
        for d in durs:
            b.append(trace_id=bytes(16), span_id=bytes(8), name="op-0",
                     service="svc", kind=2, status_code=0,
                     start_unix_nano=10**18,
                     end_unix_nano=10**18 + int(d * 1e9))
        proc.push_batch(b.build())
    check()
    assert M.fallbacks_total == fb0, \
        f"seed={SEED}: solver fell back during the fuzz run"
