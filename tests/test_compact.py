"""Device cold-tier compaction + sketch sidecars (ops/compact.py,
block/sidecar.py, db/compactor.py device route, frontend fold tier).

Differential coverage per the ISSUE: device merge vs the host compactor
on random overlapping blocks (dup trace ids, dup span ids, empty/tiny
blocks) with reader bit-parity; sidecar-fold quantile vs a full-rescan
oracle within the moments error gate; sched compaction-class
anti-starvation; plane-cache fold eviction on compaction.
"""

from __future__ import annotations

import numpy as np
import pytest

from tempo_tpu.backend.mem import MemBackend
from tempo_tpu.block.reader import BackendBlock
from tempo_tpu.block.sidecar import (
    build_sidecar,
    eligible_plan,
    merge_sidecars,
    read_sidecar,
)
from tempo_tpu.db import CompactorConfig, TempoDB, TempoDBConfig
from tempo_tpu.db import compactor as comp
from tempo_tpu.frontend import Frontend, FrontendConfig
from tempo_tpu.ops import compact as cops
from tempo_tpu.querier import Querier
from tempo_tpu.querier.querier import QuerierConfig
from tempo_tpu.ring import Ring

T0 = 1_700_000_000.0


def mkspan(tid, sid, name="op", svc="svc", t0_s=T0, dur_ms=50.0):
    t0 = int(t0_s * 1e9)
    return {"trace_id": tid, "span_id": sid, "name": name, "service": svc,
            "start_unix_nano": t0, "end_unix_nano": t0 + int(dur_ms * 1e6)}


# ---------------------------------------------------------------------------
# merge kernel vs pure-python reference
# ---------------------------------------------------------------------------

def test_merge_order_matches_reference_fuzz():
    rng = np.random.default_rng(11)
    for trial in range(6):
        n = int(rng.integers(1, 400))
        # few distinct ids → many duplicate (tid, sid) pairs across
        # "blocks" (rows), the exact shape compaction dedups
        tid = rng.integers(0, 30, (n, 16)).astype(np.uint8)
        sid = rng.integers(0, 4, (n, 8)).astype(np.uint8)
        got = cops.merge_order(tid, sid)
        ref = cops.reference_merge_order(tid, sid)
        assert np.array_equal(got, np.asarray(ref)), trial


def test_merge_order_empty_and_single():
    z16 = np.zeros((0, 16), np.uint8)
    z8 = np.zeros((0, 8), np.uint8)
    assert len(cops.merge_order(z16, z8)) == 0
    one = cops.merge_order(np.ones((1, 16), np.uint8),
                           np.ones((1, 8), np.uint8))
    assert np.array_equal(one, [0])


def test_merge_order_byte_lexicographic():
    # big-endian limbs: byte 0 must outrank byte 15 (the host oracle
    # sorts by bytes(tid); structure.id_limbs' native order would not)
    a = np.zeros((2, 16), np.uint8)
    a[0, 15] = 1   # 00..01
    a[1, 0] = 1    # 01..00
    sid = np.arange(2, dtype=np.uint8).repeat(8).reshape(2, 8)
    order = cops.merge_order(a, sid)
    assert list(order) == [0, 1]


# ---------------------------------------------------------------------------
# device compaction vs host compactor: reader bit-parity
# ---------------------------------------------------------------------------

def _overlapping_blocks(rng, n_blocks=3, n_traces=25):
    """Blocks sharing trace ids, with duplicated spans (RF overlap) and
    one near-empty block."""
    pool = []
    for i in range(n_traces):
        tid = bytes(rng.integers(0, 8, 16).astype(np.uint8))
        spans = [mkspan(tid, bytes(rng.integers(0, 256, 8).astype(np.uint8)),
                        svc=f"svc-{i % 3}", t0_s=T0 + i,
                        dur_ms=float(rng.integers(1, 500)))
                 for _ in range(int(rng.integers(1, 4)))]
        pool.append((tid, spans))
    blocks = []
    for b in range(n_blocks):
        lo = int(rng.integers(0, n_traces // 2))
        hi = int(rng.integers(lo + 1, n_traces + 1))
        blk = [(tid, [dict(s) for s in spans]) for tid, spans in pool[lo:hi]]
        blocks.append(sorted(blk, key=lambda t: t[0]))
    blocks.append(sorted(pool[:1], key=lambda t: t[0]))   # tiny block
    return blocks


def _read_rows(be, metas):
    rows = []
    for m in sorted(metas, key=lambda m: m.min_trace_id):
        tb = BackendBlock(be, m).parquet_file().read()
        cols = {c: tb.column(c).to_pylist() for c in tb.schema.names}
        rows.extend(zip(*[cols[c] for c in sorted(cols)]))
    return rows


def test_device_compaction_bit_parity_with_host():
    rng = np.random.default_rng(5)
    blocks = _overlapping_blocks(rng)

    def build():
        be = MemBackend()
        db = TempoDB(be, be, TempoDBConfig(row_group_rows=16))
        for blk in blocks:
            db.write_block("t1", blk, replication_factor=1)
        db.poll_now()
        return be, sorted(db.blocks("t1"), key=lambda m: m.block_id)

    cfg = CompactorConfig()
    be_h, metas_h = build()
    be_d, metas_d = build()
    out_h = comp.compact(be_h, be_h, "t1", metas_h, cfg)
    stats = {"blocks": 0, "spans": 0, "device_seconds": 0.0,
             "sidecars_written": 0}
    out_d = comp.compact_device(be_d, be_d, "t1", metas_d, cfg, stats)
    assert _read_rows(be_h, out_h) == _read_rows(be_d, out_d)
    assert stats["blocks"] == len(metas_d) and stats["spans"] > 0
    # sidecars born with the merged block, meta marker flipped
    assert all(m.sidecar for m in out_d)
    assert read_sidecar(be_d, "t1", out_d[0].block_id) is not None


def test_device_compaction_block_split_parity():
    # multiple output blocks: the trace/byte flush budgets must cut the
    # merged run at the same trace boundaries as the host loop
    rng = np.random.default_rng(9)
    blocks = _overlapping_blocks(rng, n_blocks=2, n_traces=30)
    cfg = CompactorConfig(max_block_objects=7)

    def run(device):
        be = MemBackend()
        db = TempoDB(be, be, TempoDBConfig(row_group_rows=16))
        for blk in blocks:
            db.write_block("t1", blk, replication_factor=1)
        db.poll_now()
        metas = sorted(db.blocks("t1"), key=lambda m: m.block_id)
        if device:
            return _read_rows(be, comp.compact_device(
                be, be, "t1", metas, cfg)), be
        return _read_rows(be, comp.compact(be, be, "t1", metas, cfg)), be

    (rows_h, _), (rows_d, _) = run(False), run(True)
    assert rows_h == rows_d


def test_db_device_route_and_cache_eviction():
    """compact_tenant_once through the device route evicts the inputs'
    plane-cache entries AND their cached fold results (satellite: the
    compact-then-query path can never serve stale folds)."""
    be = MemBackend()
    db = TempoDB(be, be, TempoDBConfig(row_group_rows=16))
    rng = np.random.default_rng(2)
    for blk in _overlapping_blocks(rng, n_blocks=2, n_traces=10):
        db.write_block("t1", blk, replication_factor=1)
    db.poll_now()
    inputs = db.blocks("t1")
    assert len(inputs) >= 2
    # warm the plane cache + the fold cache for every input block
    for m in inputs:
        db.planes.get(BackendBlock(be, m))
        db.planes.fold_put("t1", m.block_id, ("win",), [])
        assert db.planes.fold_get("t1", m.block_id, ("win",)) == []
    n = db.compact_tenant_once("t1")
    assert n >= 1
    assert db.compaction_stats["blocks"] >= 2
    assert db.compaction_stats["device_seconds"] > 0.0
    for m in inputs:
        assert db.planes.peek("t1", m.block_id) is None
        assert db.planes.fold_get("t1", m.block_id, ("win",)) is None


# ---------------------------------------------------------------------------
# sidecars: build/merge, backfill, fold vs rescan oracle
# ---------------------------------------------------------------------------

def test_sidecar_merge_and_cardinality():
    rng = np.random.default_rng(4)
    tid = rng.integers(0, 256, (400, 16)).astype(np.uint8)
    svc = np.array(["a", "b"] * 200)
    nam = np.array(["x"] * 400)
    dur = rng.integers(10_000, 10_000_000, 400)
    sc = build_sidecar(svc, nam, dur, tid)
    assert sc.total_spans == 400 and set(sc.series) == {("a", "x"),
                                                        ("b", "x")}
    est = sc.trace_cardinality()
    assert 0.8 * 400 <= est <= 1.2 * 400
    both = merge_sidecars(sc, sc)
    assert both.total_spans == 800
    # self-merge is idempotent for distinct-count (HLL max-merge)
    assert abs(both.trace_cardinality() - est) < 1e-6


def test_eligible_plan_gating():
    assert eligible_plan("{ } | rate()") is not None
    p = eligible_plan("{ } | quantile_over_time(duration, .5) "
                      "by (resource.service.name)")
    assert p is not None and p.quantile and p.group_axes == ("service",)
    # conditions, non-duration attrs, unsupported group axes → no fold
    assert eligible_plan('{ span.foo = "x" } | rate()') is None
    assert eligible_plan(
        "{ } | quantile_over_time(span.bytes, .5)") is None
    assert eligible_plan("{ } | rate() by (span.foo)") is None
    assert eligible_plan("{ } | histogram_over_time(duration)") is None


def _fold_stack(rng, n_blocks=3, spans_per_block=60):
    clock = [T0 + 3600.0]
    now = lambda: clock[0]
    be = MemBackend()
    db = TempoDB(be, be, now=now)
    durs = []
    for blk in range(n_blocks):
        traces = []
        for i in range(spans_per_block):
            tid = bytes([blk * 64 + (i % 50), 9] + [0] * 14)
            d = float(rng.lognormal(np.log(50), 0.5))
            durs.append(d)
            traces.append((tid, [mkspan(tid, bytes(
                rng.integers(0, 256, 8).astype(np.uint8)),
                svc=f"svc-{blk % 2}", t0_s=T0 + i * 3, dur_ms=d)]))
        db.write_block("t1", sorted(traces, key=lambda t: t[0]),
                       replication_factor=1)
    db.poll_now()
    assert db.backfill_sidecars_once("t1", limit=n_blocks) == n_blocks
    db.poll_now()
    ring = Ring(replication_factor=1, now=now)
    q = Querier(db, ring, {}, cfg=QuerierConfig(rf=1))
    return db, q, now, np.array(durs)


def test_sidecar_fold_quantile_within_moments_gate():
    rng = np.random.default_rng(17)
    db, q, now, durs = _fold_stack(rng)
    fe = Frontend(db, q, cfg=FrontendConfig(), now=now)
    series = fe.query_range("t1", "{ } | quantile_over_time(duration, .5, .9)",
                            start_s=T0 - 60, end_s=T0 + 600, step_s=660.0)
    folds0 = db.compaction_stats["sidecar_folds"]
    assert folds0 > 0 and db.compaction_stats["sidecar_fallbacks"] == 0
    got = {dict(s.labels)["p"]: float(np.nansum(s.samples)) for s in series}
    for qv in (0.5, 0.9):
        exact = np.quantile(durs, qv) / 1e3          # ms → s
        rel = abs(got[qv] - exact) / exact
        rank = abs(np.mean(durs / 1e3 <= got[qv]) - qv)
        assert min(rel, rank) <= 0.05, (qv, got[qv], exact, rel, rank)
    # second query is served from the fold cache
    fe.query_range("t1", "{ } | quantile_over_time(duration, .5, .9)",
                   start_s=T0 - 60, end_s=T0 + 600, step_s=660.0)
    assert db.planes.fold_hits > 0


def test_sidecar_fold_rate_matches_rescan_exactly():
    rng = np.random.default_rng(23)
    db, q, now, _ = _fold_stack(rng, n_blocks=2, spans_per_block=40)
    fe_fold = Frontend(db, q, cfg=FrontendConfig(), now=now)
    fe_scan = Frontend(db, q, cfg=FrontendConfig(sidecar_folds=False),
                       now=now)
    for query in ("{ } | rate()",
                  "{ } | rate() by (resource.service.name)"):
        a = fe_fold.query_range("t1", query, start_s=T0 - 60,
                                end_s=T0 + 600, step_s=660.0)
        b = fe_scan.query_range("t1", query, start_s=T0 - 60,
                                end_s=T0 + 600, step_s=660.0)
        ta = {s.labels: float(np.nansum(s.samples)) for s in a}
        tb = {s.labels: float(np.nansum(s.samples)) for s in b}
        assert set(ta) == set(tb)
        for k in ta:
            assert ta[k] == pytest.approx(tb[k], rel=1e-9), (query, k)


def test_fold_ineligible_block_falls_back_to_scan():
    # one block loses its sidecar marker → that block scans, the others
    # fold, and the combined answer still matches the all-scan answer
    rng = np.random.default_rng(29)
    db, q, now, _ = _fold_stack(rng, n_blocks=3, spans_per_block=30)
    metas = db.blocklist.metas("t1")
    metas[0].sidecar = False
    fe = Frontend(db, q, cfg=FrontendConfig(), now=now)
    fe_scan = Frontend(db, q, cfg=FrontendConfig(sidecar_folds=False),
                       now=now)
    a = fe.query_range("t1", "{ } | rate()", start_s=T0 - 60,
                       end_s=T0 + 600, step_s=660.0)
    b = fe_scan.query_range("t1", "{ } | rate()", start_s=T0 - 60,
                            end_s=T0 + 600, step_s=660.0)
    assert float(np.nansum(a[0].samples)) == pytest.approx(
        float(np.nansum(b[0].samples)), rel=1e-9)


def test_blockbuilder_emits_sidecar_at_cut():
    from tempo_tpu.blockbuilder import BlockBuilder, BlockBuilderConfig
    from tempo_tpu.ingest.bus import Bus
    from tempo_tpu.ingest.encoding import produce_traces
    from tempo_tpu.ops.hashing import token_for

    be = MemBackend()
    bus = Bus(n_partitions=1)
    tid = b"\x42" * 16
    mat = np.frombuffer(tid, np.uint8).reshape(1, 16)
    produce_traces(bus, "t1", [(tid, [mkspan(tid, b"\x01" * 8)])],
                   token_for("t1", mat))
    bb = BlockBuilder(bus, be, BlockBuilderConfig())
    assert bb.consume_cycle() == 1
    db = TempoDB(be, be)
    db.poll_now()
    metas = db.blocks("t1")
    assert len(metas) == 1 and metas[0].sidecar
    sc = read_sidecar(be, "t1", metas[0].block_id)
    assert sc is not None and sc.total_spans == 1


def test_backfill_skips_done_and_respects_limit():
    rng = np.random.default_rng(31)
    be = MemBackend()
    db = TempoDB(be, be)
    for blk in _overlapping_blocks(rng, n_blocks=3, n_traces=6):
        db.write_block("t1", blk, replication_factor=1)
    db.poll_now()
    assert db.backfill_sidecars_once("t1", limit=2) == 2
    db.poll_now()
    assert db.backfill_sidecars_once("t1", limit=10) == 2  # the rest
    db.poll_now()
    assert db.backfill_sidecars_once("t1", limit=10) == 0  # all done
    assert db.compaction_stats["sidecars_written"] == 4


# ---------------------------------------------------------------------------
# sched: compaction-class minimum dispatch share
# ---------------------------------------------------------------------------

def _submit_compaction(sc, order, tag="compaction"):
    from tempo_tpu import sched as S
    job = S.Job(priority=S.PRIO_COMPACTION, kernel=tag,
                fn=lambda: order.append(tag))
    with sc._cond:
        sc._queues[S.PRIO_COMPACTION].append(job)


def test_compaction_min_share_survives_sustained_ingest():
    from tempo_tpu.sched import DeviceScheduler, SchedConfig

    sc = DeviceScheduler(SchedConfig(batch_window_ms=0.0,
                                     compaction_min_share=0.25),
                         start_worker=False)
    order = []
    _submit_compaction(sc, order)
    for i in range(8):
        sc.submit_rows("k", "m", (np.zeros(4, np.int32),), 4,
                       lambda s: order.append("ingest"), pads=(-1,))
        sc.drain_once()
    # never a fully-idle drain, yet the share valve forced it through
    assert "compaction" in order
    assert order.index("compaction") <= int(1 / 0.25) + 1
    assert sc.comp_forced_total >= 1


def test_compaction_share_zero_starves_under_load():
    from tempo_tpu.sched import DeviceScheduler, SchedConfig

    sc = DeviceScheduler(SchedConfig(batch_window_ms=0.0,
                                     compaction_min_share=0.0),
                         start_worker=False)
    order = []
    _submit_compaction(sc, order)
    for i in range(40):
        sc.submit_rows("k", "m", (np.zeros(4, np.int32),), 4,
                       lambda s: order.append("ingest"), pads=(-1,))
        sc.drain_once()
    assert "compaction" not in order      # strict idle-only semantics
    sc.drain_once()                       # idle → finally runs
    assert order[-1] == "compaction"


def test_compaction_metrics_families_registered():
    be = MemBackend()
    db = TempoDB(be, be)
    text = db.obs.render()
    for fam in ("blocks", "spans", "device_seconds", "sidecars_written",
                "sidecar_folds", "sidecar_fallbacks"):
        assert f"tempo_compaction_{fam}_total" in text, fam
