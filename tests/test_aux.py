"""Aux subsystems: usage tracker, hedged requests, cache roles."""

from __future__ import annotations

import threading
import time

import pytest

from tempo_tpu.backend.cache import CacheProvider, CachingReader, ROLE_BLOOM
from tempo_tpu.backend.mem import MemBackend
from tempo_tpu.backend.raw import KeyPath
from tempo_tpu.utils.hedging import HedgedMetrics, hedged_call
from tempo_tpu.utils.usage import OVERFLOW, UsageTracker, UsageTrackerConfig


def test_usage_tracker_dimensions_and_overflow():
    t = UsageTracker(UsageTrackerConfig(dimensions=("service",),
                                        max_cardinality=3))
    for i in range(5):
        t.observe("acme", [{"service": f"svc-{i}", "attrs": {}}])
    text = t.prometheus_text()
    assert 'service="svc-0"' in text
    assert OVERFLOW in text  # 4th/5th distinct services bucket to overflow
    assert 'tenant="acme"' in text
    # attr-sourced dimension
    t2 = UsageTracker(UsageTrackerConfig(dimensions=("team",)))
    t2.observe("acme", [{"attrs": {"team": "payments"}}], size_bytes=1000)
    assert 'team="payments"' in t2.prometheus_text()
    assert "1000" in t2.prometheus_text()


def test_hedged_call_fast_path_no_hedge():
    m = HedgedMetrics()
    assert hedged_call(lambda: 42, delay_s=0.5, metrics=m) == 42
    assert m.requests_total == 1 and m.hedged_total == 0


def test_hedged_call_hedges_slow_first_attempt():
    m = HedgedMetrics()
    calls = []
    lock = threading.Lock()

    def fn():
        with lock:
            calls.append(None)
            n = len(calls)
        if n == 1:
            time.sleep(1.0)  # slow first attempt
            return "slow"
        return "fast"

    t0 = time.perf_counter()
    out = hedged_call(fn, delay_s=0.05, metrics=m)
    assert out == "fast"
    assert time.perf_counter() - t0 < 0.8
    assert m.hedged_total == 1


def test_hedged_call_propagates_error_after_all_fail():
    def boom():
        raise RuntimeError("nope")
    with pytest.raises(RuntimeError, match="nope"):
        hedged_call(boom, delay_s=0.01)


def test_usage_label_escaping():
    t = UsageTracker(UsageTrackerConfig(dimensions=("service",)))
    evil = 'a"} 999\ninjected_metric{x="y'
    t.observe("ten\"ant", [{"service": evil}])
    text = t.prometheus_text()
    # no forged exposition line: every physical line is one of ours (a
    # sample or HELP/TYPE metadata from the shared obs renderer), raw
    # newlines/quotes in values are escaped
    for line in text.strip().splitlines():
        assert line.startswith(("tempo_usage_tracker_", "# ")), line
    assert '\\n' in text and '\\"' in text
    # and the output is well-formed exposition end to end
    from tempo_tpu.obs import parse_exposition
    parse_exposition(text)


def test_hedged_reader_wraps_reads():
    from tempo_tpu.utils.hedging import HedgedReader

    be = MemBackend()
    kp = KeyPath(("t", "b"))
    be.write("data", kp, b"hello")
    r = HedgedReader(be, delay_s=0.5)
    assert r.read("data", kp) == b"hello"
    assert r.read_range("data", kp, 1, 3) == b"ell"
    assert r.metrics.requests_total == 2 and r.metrics.hedged_total == 0


def test_forwarder_tee_filter_and_payload():
    from tempo_tpu.distributor.forwarder import (
        Forwarder,
        ForwarderConfig,
        ForwarderManager,
        otlp_json_payload,
    )

    got = []
    fwd = Forwarder(ForwarderConfig(
        name="tee", filter={"include": {"service": "svc-a"},
                            "exclude": {"name": "noisy"}}),
        sink=got.extend)
    mgr = ForwarderManager()
    mgr.register("t1", fwd)
    spans = [
        {"trace_id": b"\x01" * 16, "span_id": b"\x01" * 8, "name": "ok",
         "service": "svc-a", "start_unix_nano": 1, "end_unix_nano": 2,
         "attrs": {"k": 1}},
        {"trace_id": b"\x02" * 16, "span_id": b"\x02" * 8, "name": "noisy",
         "service": "svc-a", "start_unix_nano": 1, "end_unix_nano": 2},
        {"trace_id": b"\x03" * 16, "span_id": b"\x03" * 8, "name": "ok",
         "service": "svc-b", "start_unix_nano": 1, "end_unix_nano": 2},
    ]
    mgr.offer("t1", spans)
    mgr.offer("other-tenant", spans)  # not registered: no-op
    fwd.flush()
    mgr.shutdown()
    assert len(got) == 1 and got[0]["name"] == "ok"
    assert fwd.forwarded == 1
    payload = otlp_json_payload(got)
    sp = payload["resourceSpans"][0]["scopeSpans"][0]["spans"][0]
    assert sp["traceId"] == "01" * 16
    assert sp["attributes"] == [{"key": "k", "value": {"intValue": "1"}}]


def test_caching_reader_roles():
    be = MemBackend()
    kp = KeyPath(("t1", "blk"))
    be.write("bloom-0", kp, b"BLOOMDATA")
    be.write("data.parquet", kp, b"0123456789")
    prov = CacheProvider()
    r = CachingReader(be, prov)
    assert r.read("bloom-0", kp) == b"BLOOMDATA"
    assert r.read("bloom-0", kp) == b"BLOOMDATA"
    c = prov.cache_for(ROLE_BLOOM)
    assert c.hits == 1 and c.misses == 1
    # page ranges cached under page role
    assert r.read_range("data.parquet", kp, 2, 3) == b"234"
    assert r.read_range("data.parquet", kp, 2, 3) == b"234"


# -- usage stats (pkg/usagestats analog) ------------------------------------

def test_usage_reporter_leader_election_and_report():
    import json

    from tempo_tpu.backend.mem import MemBackend
    from tempo_tpu.ring.kv import KVStore
    from tempo_tpu.backend.raw import KeyPath
    from tempo_tpu.utils.usagestats import REPORT_NAME, UsageReporter

    clock = [1000.0]
    now = lambda: clock[0]
    kv = KVStore()
    be = MemBackend()
    a = UsageReporter(kv, be, instance_id="a", lease_s=90, now=now)
    b = UsageReporter(kv, be, instance_id="b", lease_s=90, now=now)

    # one leader; the seed is cluster-wide stable
    assert a.try_acquire_leadership()
    assert not b.try_acquire_leadership()
    seed1, seed2 = a.get_or_create_seed(), b.get_or_create_seed()
    assert seed1 == seed2

    a.inc_stat("spans", 41)
    a.inc_stat("spans")
    a.set_stat("target", "all")
    assert a.report_once()
    rep = json.loads(be.read(REPORT_NAME, KeyPath(("usage-stats",))))
    assert rep["clusterID"] == seed1
    assert rep["metrics"]["spans"] == 42
    assert rep["target"] == "all"
    assert not b.report_once()          # not leader: no write

    # lease lapses -> the other member takes over
    clock[0] += 200
    assert b.try_acquire_leadership()
    assert not a.try_acquire_leadership()
    assert b.report_once()


def test_usage_reporter_over_replicated_kv():
    """Leader election against the replicated KV routes through ONE
    member (cas_primary): two contenders racing the same empty lease get
    exactly one winner, and the cluster seed is minted once."""
    from tempo_tpu.backend.mem import MemBackend
    from tempo_tpu.ring.kv import KVStore, ReplicatedKVStore, _LocalEndpoint
    from tempo_tpu.utils.usagestats import UsageReporter

    stores = [KVStore() for _ in range(3)]
    clock = [50.0]
    now = lambda: clock[0]

    def client():
        return ReplicatedKVStore([_LocalEndpoint(s) for s in stores])

    a = UsageReporter(client(), MemBackend(), instance_id="a", now=now)
    b = UsageReporter(client(), MemBackend(), instance_id="b", now=now)
    # concurrent contention for the same empty lease: exactly one winner
    import threading
    wins = {}
    barrier = threading.Barrier(2)
    def contend(r, key):
        barrier.wait()
        wins[key] = r.try_acquire_leadership()
    ts = [threading.Thread(target=contend, args=(r, k))
          for r, k in ((a, "a"), (b, "b"))]
    [t.start() for t in ts]; [t.join() for t in ts]
    assert sorted(wins.values()) == [False, True], wins
    # renewal keeps it with the winner
    clock[0] += 30
    winner, loser = (a, b) if wins["a"] else (b, a)
    assert winner.try_acquire_leadership()
    assert not loser.try_acquire_leadership()
    # the seed is minted once, cluster-wide
    assert a.get_or_create_seed() == b.get_or_create_seed()


# -- data quality warnings (pkg/dataquality analog) -------------------------

def test_dataquality_warnings():
    from tempo_tpu.utils.dataquality import (REASON_FUTURE, REASON_PAST,
                                             DataQuality)

    now = lambda: 1_000_000_000.0
    dq = DataQuality(now=now)
    ns = lambda s: int(s * 1e9)
    spans = [
        {"start_unix_nano": ns(1_000_000_000)},          # fine
        {"start_unix_nano": ns(1_000_000_000 + 3 * 3600)},   # future
        {"start_unix_nano": ns(1_000_000_000 - 15 * 86400)}, # way past
        {"start_unix_nano": 0},                          # absent: ignored
    ]
    dq.observe_spans("t1", spans)
    snap = dq.snapshot()
    assert snap[("t1", REASON_FUTURE)] == 1
    assert snap[("t1", REASON_PAST)] == 1


def test_dataquality_exposed_on_metrics(tmp_path):
    import urllib.request

    from tempo_tpu.app import App
    from tempo_tpu.app.api import serve
    from tempo_tpu.app.config import Config
    from tempo_tpu.utils.dataquality import REASON_FUTURE

    import socket
    s = socket.socket(); s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]; s.close()
    cfg = Config(target="all")
    cfg.storage.backend = "mem"
    cfg.storage.wal_path = str(tmp_path / "wal")
    cfg.generator.localblocks.data_dir = str(tmp_path / "lb")
    cfg.server.http_listen_port = port
    app = App(cfg)
    srv = serve(app, block=False)
    try:
        app.distributor.dataquality.warn("single-tenant", REASON_FUTURE, 3)
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        assert 'tempo_warnings_total{tenant="single-tenant",' \
               f'reason="{REASON_FUTURE}"}} 3' in body
    finally:
        srv.shutdown()
        app.shutdown()


# -- self-tracing (cmd/tempo/main.go:227-281 analog) ------------------------

def test_self_tracing_dogfood(tmp_path):
    """The app traces itself INTO ITSELF: spans from a push/search land as
    real traces under the self-tenant, queryable like any other tenant."""
    import socket
    import time
    import urllib.request

    from tempo_tpu.app import App
    from tempo_tpu.app.api import serve
    from tempo_tpu.app.config import Config
    from tempo_tpu.utils import tracing

    s = socket.socket(); s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]; s.close()
    cfg = Config(target="all")
    cfg.storage.backend = "mem"
    cfg.storage.wal_path = str(tmp_path / "wal")
    cfg.generator.localblocks.data_dir = str(tmp_path / "lb")
    cfg.server.http_listen_port = port
    cfg.self_tracing_endpoint = f"http://127.0.0.1:{port}"
    app = App(cfg)
    app.start_loops()
    srv = serve(app, block=False)
    try:
        assert not isinstance(tracing.tracer(), tracing.NoopTracer)
        # trigger traced entry points
        t0 = int((time.time() - 3) * 1e9)
        otlp = {"resourceSpans": [{"scopeSpans": [{"spans": [{
            "traceId": "ab" * 16, "spanId": "cd" * 8, "name": "user-op",
            "startTimeUnixNano": str(t0),
            "endTimeUnixNano": str(t0 + 1_000_000)}]}]}]}
        import json as _json
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/traces",
            data=_json.dumps(otlp).encode(),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=10).close()
        app.frontend.search("single-tenant", "{ }", limit=5)
        # flush self-spans into this very process
        assert tracing.tracer().flush() > 0
        # nested child spans share the parent's trace
        with tracing.span("outer") as outer:
            with tracing.span("inner") as inner:
                assert inner.trace_id == outer.trace_id
                assert inner.parent_span_id == outer.span_id
        tracing.tracer().flush()
        # the self-tenant now holds framework spans, queryable
        names = set()
        inst = app.ingester.instance("tempo-self")
        for _tid, lt in inst.live.traces.items():
            for sp in lt.spans:
                names.add(sp["name"])
        assert "distributor.PushSpans" in names, names
        assert "frontend.Search" in names, names
        # traceparent propagation surface
        with tracing.span("rpc-client"):
            tp = tracing.tracer().traceparent()
            assert tp and tp.startswith("00-")
    finally:
        srv.shutdown()
        app.shutdown()


def test_debug_profile_endpoints(tmp_path):
    import socket
    import urllib.request

    from tempo_tpu.app import App
    from tempo_tpu.app.api import serve
    from tempo_tpu.app.config import Config

    s = socket.socket(); s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]; s.close()
    cfg = Config(target="all")
    cfg.storage.backend = "mem"
    cfg.storage.wal_path = str(tmp_path / "wal")
    cfg.generator.localblocks.data_dir = str(tmp_path / "lb")
    cfg.server.http_listen_port = port
    app = App(cfg)
    srv = serve(app, block=False)
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/threads", timeout=10
        ).read().decode()
        assert "--- thread" in body and "serve_forever" in body
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/profile?seconds=0.3",
            timeout=10).read().decode()
        assert body.startswith("samples:")
    finally:
        srv.shutdown()
        app.shutdown()


def test_dashboards_generated_from_single_source():
    """The four ops dashboards are GENERATED (operations/gen_dashboards.py,
    the tempo-mixin dashboards.libsonnet analog) — committed JSON must
    match the generator exactly so panels cannot drift from the spec."""
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "operations",
                                      "gen_dashboards.py"), "--check"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr or proc.stdout


def test_runbook_covers_every_alert():
    """Every alert in operations/alerts.yaml has a matching `## <Alert>`
    runbook section AND a runbook_url annotation pointing at it
    (reference: operations/tempo-mixin/runbook.md maps alerts to operator
    actions)."""
    import os
    import re

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    alerts_text = open(os.path.join(root, "operations",
                                    "alerts.yaml")).read()
    runbook = open(os.path.join(root, "operations", "runbook.md")).read()
    alerts = re.findall(r"- alert: (\w+)", alerts_text)
    assert len(alerts) >= 9
    sections = set(re.findall(r"^## (\w+)", runbook, re.M))
    urls = set(re.findall(r"runbook_url: \S*#(\w+)", alerts_text))
    for a in alerts:
        assert a in sections, f"runbook section missing for alert {a}"
        assert a.lower() in urls, f"runbook_url missing for alert {a}"


# -- shared memcached cache tier (round 5, pkg/cache/memcached analog) -------

def test_memcached_client_roundtrip_and_sanitization():
    from tempo_tpu.backend.memcached import MemcachedCache, sanitize_key
    from tests.mock_memcached import start_mock_memcached

    srv, port, mock = start_mock_memcached()
    try:
        c = MemcachedCache(f"127.0.0.1:{port}")
        assert c.get("missing") is None and c.misses == 1
        c.put("k1", b"v1")
        c.flush()
        assert c.get("k1") == b"v1" and c.hits == 1
        # long + unsafe keys sanitize to sha1 (mock REJECTS illegal keys,
        # so a sloppy client would fail here, not silently miss)
        long_key = "tenant/" + "x" * 300 + " with spaces"
        c.put(long_key, b"v2")
        c.flush()
        assert c.get(long_key) == b"v2"
        assert mock.bad_requests == 0
        assert sanitize_key(long_key) != long_key.encode()
        c.close()
    finally:
        srv.shutdown()


def test_memcached_write_behind_drops_when_full():
    from tempo_tpu.backend.memcached import MemcachedCache

    # no server at this address: the writer can't drain, the queue fills,
    # further puts DROP (counted) instead of blocking the read path
    c = MemcachedCache("127.0.0.1:1", write_back_buffer=4)
    for i in range(64):
        c.put(f"k{i}", b"v")
    assert c.dropped_writes > 0
    assert c.get("k0") is None          # dead server degrades to miss
    c.close()


def test_memcached_cross_instance_shared_cache():
    """Two TempoDB instances with SEPARATE processes' worth of cache state
    share one memcached: blocks written+read through instance A leave
    bloom/footer entries that instance B's reads hit (scale-out read perf
    depends on this — in-process LRUs cannot give cross-replica hits)."""
    import numpy as np
    from tempo_tpu.backend.cache import CacheProvider, CachingReader
    from tempo_tpu.backend.memcached import MemcachedCache
    from tempo_tpu.backend.mem import MemBackend
    from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig
    from tests.mock_memcached import start_mock_memcached

    srv, port, mock = start_mock_memcached()
    try:
        be = MemBackend()
        roles = ("bloom", "parquet-footer")

        def mk_db():
            shared = MemcachedCache(f"127.0.0.1:{port}")
            prov = CacheProvider(caches={r: shared for r in roles})
            return TempoDB(CachingReader(be, prov), be,
                           TempoDBConfig(device_plane=False)), shared

        db_a, ca = mk_db()
        db_b, cb = mk_db()
        rng = np.random.default_rng(3)
        tid0 = None
        traces = []
        for i in range(50):
            tid = rng.bytes(16)
            tid0 = tid0 or tid
            start = 1_700_000_000_000_000_000 + i * 10**9
            traces.append((tid, [{
                "trace_id": tid, "span_id": rng.bytes(8), "name": "op",
                "service": "svc", "kind": 2, "status_code": 0,
                "start_unix_nano": start,
                "end_unix_nano": start + 10**6}]))
        traces.sort(key=lambda t: t[0])   # blocks are trace-id ordered
        db_a.write_block("t", traces, replication_factor=1)
        db_a.poll_now()
        db_b.poll_now()
        assert db_a.find_trace_by_id("t", tid0)   # A populates the tier
        ca.flush()
        before = cb.hits
        assert db_b.find_trace_by_id("t", tid0)   # B hits A's entries
        assert cb.hits > before, (cb.hits, cb.misses)
        assert mock.sets > 0 and mock.gets > 0
        db_a.shutdown(); db_b.shutdown()
    finally:
        srv.shutdown()


def test_redis_cache_client_roundtrip_and_expiry():
    """The RESP2 redis variant shares the write-behind + degradation
    semantics with the memcached tier (pkg/cache/redis_client.go analog);
    the strict mock rejects malformed framing."""
    from tempo_tpu.backend.memcached import RedisCache
    from tests.mock_memcached import start_mock_redis

    srv, port, mock = start_mock_redis()
    try:
        c = RedisCache(f"127.0.0.1:{port}", expiration_s=60)
        assert c.get("missing") is None and c.misses == 1
        c.put("k1", b"v1")
        c.flush()
        assert c.get("k1") == b"v1" and c.hits == 1
        assert mock.sets == 1 and mock.gets == 2
        # concurrent readers: per-thread connections, no cross-talk
        import threading as _t
        errs = []

        def reader(i):
            for _ in range(50):
                if c.get("k1") != b"v1":
                    errs.append(i)

        ts = [_t.Thread(target=reader, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs
        c.close()
    finally:
        srv.shutdown()


def test_app_wires_shared_cache_tier(tmp_path):
    from tempo_tpu.app import App
    from tempo_tpu.app.config import Config
    from tempo_tpu.backend.memcached import MemcachedCache, RedisCache
    from tests.mock_memcached import start_mock_redis

    srv, port, mock = start_mock_redis()
    try:
        cfg = Config(target="querier")
        cfg.storage.backend = "mem"
        cfg.storage.wal_path = str(tmp_path / "wal")
        cfg.storage.redis_addrs = f"127.0.0.1:{port}"
        app = App(cfg)
        c = app.cache_provider.cache_for("bloom")
        assert isinstance(c, RedisCache)
        c.put("k", b"v")
        c.flush()
        assert c.get("k") == b"v" and mock.sets == 1
        app.shutdown()
    finally:
        srv.shutdown()
