"""Aux subsystems: usage tracker, hedged requests, cache roles."""

from __future__ import annotations

import threading
import time

import pytest

from tempo_tpu.backend.cache import CacheProvider, CachingReader, ROLE_BLOOM
from tempo_tpu.backend.mem import MemBackend
from tempo_tpu.backend.raw import KeyPath
from tempo_tpu.utils.hedging import HedgedMetrics, hedged_call
from tempo_tpu.utils.usage import OVERFLOW, UsageTracker, UsageTrackerConfig


def test_usage_tracker_dimensions_and_overflow():
    t = UsageTracker(UsageTrackerConfig(dimensions=("service",),
                                        max_cardinality=3))
    for i in range(5):
        t.observe("acme", [{"service": f"svc-{i}", "attrs": {}}])
    text = t.prometheus_text()
    assert 'service="svc-0"' in text
    assert OVERFLOW in text  # 4th/5th distinct services bucket to overflow
    assert 'tenant="acme"' in text
    # attr-sourced dimension
    t2 = UsageTracker(UsageTrackerConfig(dimensions=("team",)))
    t2.observe("acme", [{"attrs": {"team": "payments"}}], size_bytes=1000)
    assert 'team="payments"' in t2.prometheus_text()
    assert "1000" in t2.prometheus_text()


def test_hedged_call_fast_path_no_hedge():
    m = HedgedMetrics()
    assert hedged_call(lambda: 42, delay_s=0.5, metrics=m) == 42
    assert m.requests_total == 1 and m.hedged_total == 0


def test_hedged_call_hedges_slow_first_attempt():
    m = HedgedMetrics()
    calls = []
    lock = threading.Lock()

    def fn():
        with lock:
            calls.append(None)
            n = len(calls)
        if n == 1:
            time.sleep(1.0)  # slow first attempt
            return "slow"
        return "fast"

    t0 = time.perf_counter()
    out = hedged_call(fn, delay_s=0.05, metrics=m)
    assert out == "fast"
    assert time.perf_counter() - t0 < 0.8
    assert m.hedged_total == 1


def test_hedged_call_propagates_error_after_all_fail():
    def boom():
        raise RuntimeError("nope")
    with pytest.raises(RuntimeError, match="nope"):
        hedged_call(boom, delay_s=0.01)


def test_usage_label_escaping():
    t = UsageTracker(UsageTrackerConfig(dimensions=("service",)))
    evil = 'a"} 999\ninjected_metric{x="y'
    t.observe("ten\"ant", [{"service": evil}])
    text = t.prometheus_text()
    # no forged exposition line: every physical line is one of ours, raw
    # newlines/quotes in values are escaped
    for line in text.strip().splitlines():
        assert line.startswith("tempo_usage_tracker_")
    assert '\\n' in text and '\\"' in text


def test_hedged_reader_wraps_reads():
    from tempo_tpu.utils.hedging import HedgedReader

    be = MemBackend()
    kp = KeyPath(("t", "b"))
    be.write("data", kp, b"hello")
    r = HedgedReader(be, delay_s=0.5)
    assert r.read("data", kp) == b"hello"
    assert r.read_range("data", kp, 1, 3) == b"ell"
    assert r.metrics.requests_total == 2 and r.metrics.hedged_total == 0


def test_forwarder_tee_filter_and_payload():
    from tempo_tpu.distributor.forwarder import (
        Forwarder,
        ForwarderConfig,
        ForwarderManager,
        otlp_json_payload,
    )

    got = []
    fwd = Forwarder(ForwarderConfig(
        name="tee", filter={"include": {"service": "svc-a"},
                            "exclude": {"name": "noisy"}}),
        sink=got.extend)
    mgr = ForwarderManager()
    mgr.register("t1", fwd)
    spans = [
        {"trace_id": b"\x01" * 16, "span_id": b"\x01" * 8, "name": "ok",
         "service": "svc-a", "start_unix_nano": 1, "end_unix_nano": 2,
         "attrs": {"k": 1}},
        {"trace_id": b"\x02" * 16, "span_id": b"\x02" * 8, "name": "noisy",
         "service": "svc-a", "start_unix_nano": 1, "end_unix_nano": 2},
        {"trace_id": b"\x03" * 16, "span_id": b"\x03" * 8, "name": "ok",
         "service": "svc-b", "start_unix_nano": 1, "end_unix_nano": 2},
    ]
    mgr.offer("t1", spans)
    mgr.offer("other-tenant", spans)  # not registered: no-op
    fwd.flush()
    mgr.shutdown()
    assert len(got) == 1 and got[0]["name"] == "ok"
    assert fwd.forwarded == 1
    payload = otlp_json_payload(got)
    sp = payload["resourceSpans"][0]["scopeSpans"][0]["spans"][0]
    assert sp["traceId"] == "01" * 16
    assert sp["attributes"] == [{"key": "k", "value": {"intValue": "1"}}]


def test_caching_reader_roles():
    be = MemBackend()
    kp = KeyPath(("t1", "blk"))
    be.write("bloom-0", kp, b"BLOOMDATA")
    be.write("data.parquet", kp, b"0123456789")
    prov = CacheProvider()
    r = CachingReader(be, prov)
    assert r.read("bloom-0", kp) == b"BLOOMDATA"
    assert r.read("bloom-0", kp) == b"BLOOMDATA"
    c = prov.cache_for(ROLE_BLOOM)
    assert c.hits == 1 and c.misses == 1
    # page ranges cached under page role
    assert r.read_range("data.parquet", kp, 2, 3) == b"234"
    assert r.read_range("data.parquet", kp, 2, 3) == b"234"
